"""Cache-layout abstraction for the serving engine: dense fixed slots
vs a paged block pool.

The engine's original slot math reserved worst-case KV memory per slot
— ``max_slots × (sinks + window | max_len)`` rows per layer —
so HBM scaled with *capacity*, not *live tokens* (ROADMAP Open item 1).
This module factors that math into two host-side layout objects:

* :class:`DenseLayout` — the original contiguous per-slot rows.  No
  allocator: every slot owns its rows for the engine's lifetime.
* :class:`PagedLayout` — a vLLM-style shared pool of fixed-size KV
  blocks plus a per-slot page table.  Blocks are allocated as a
  request's cursor advances and returned to the pool on EOS, so the
  *live* KV footprint tracks live tokens.  Completed prompt blocks are
  keyed by token-prefix hash and refcounted (:class:`BlockPool`), so a
  shared system prompt prefills once and later admissions start from
  the cached blocks.

Everything here is HOST bookkeeping (plain ints and dicts — no jax):
the device side carries the page table as int32 *data* inside the slot
cache, which is what keeps page indirection out of compiled-program
shapes (arXiv:1810.09868's full-program lesson; the engine's
ONE-decode-compile invariant survives because page-table churn feeds
the same compiled programs).  Sharing is restricted to FULL,
exact-match prompt blocks and shared blocks are never written again —
the divergence block is re-prefilled into a fresh block, i.e.
copy-on-write without a device-side copy.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["DenseLayout", "PagedLayout", "BlockPool", "prefix_digests",
           "KV_STORE_BYTES", "kv_row_bytes", "reserved_kv_bytes"]


def prefix_digests(tokens: Sequence[int], block_size: int) -> List[bytes]:
    """Chain digest per FULL block of ``tokens``: digest *i* commits to
    every token in blocks ``0..i`` (a prefix hash, not a content hash),
    so equal digests imply equal whole prefixes — the property that
    makes a cached block's K/V valid for a new request (K/V at position
    p depends on ALL tokens ≤ p)."""
    out: List[bytes] = []
    h = b""
    full = len(tokens) // block_size
    for i in range(full):
        blk = tokens[i * block_size:(i + 1) * block_size]
        m = hashlib.sha1(h)
        m.update(b"".join(int(t).to_bytes(4, "little", signed=True)
                          for t in blk))
        h = m.digest()
        out.append(h)
    return out


#: bytes per stored K or V element under each quant scenario (None =
#: the model's compute itemsize); quantized scenarios additionally
#: carry a per-row-per-head f32 scale (``models.transformer_lm
#: .quantize_kv``)
KV_STORE_BYTES = {"none": None, "int8": 1, "fp8": 1}


def kv_row_bytes(hkv: int, head_dim: int, kv_quant: str,
                 compute_itemsize: int) -> int:
    """HBM bytes one cache row (K + V, all KV heads) costs per layer:
    stored values plus the sibling scale rows for quantized scenarios —
    the sizing model behind the engine's measured ``kv_cache_bytes``
    (the bytes-halved test pins the two against each other)."""
    if kv_quant not in KV_STORE_BYTES:
        raise ValueError(
            f"unknown kv_quant {kv_quant!r} ({'|'.join(KV_STORE_BYTES)})")
    item = KV_STORE_BYTES[kv_quant] or compute_itemsize
    per = hkv * head_dim * item
    if KV_STORE_BYTES[kv_quant]:
        per += hkv * 4  # f32 scale per row per head
    return 2 * per  # K and V


def reserved_kv_bytes(layout, depth: int, hkv: int, head_dim: int,
                      compute_itemsize: int) -> int:
    """Total HBM bytes a layout's KV storage reserves across ``depth``
    layers — THE sizing model.  ``layout.reserved_rows()`` supplies the
    per-layer row count each layout actually allocates (dense: every
    slot's rows; paged: the whole block pool, shared), and
    :func:`kv_row_bytes` prices one row including the quantization
    scale leaves.  The engine's MEASURED ``kv_cache_bytes()`` is
    cross-checked against this figure (its ``predicted`` key; parity
    pinned by test in both layouts for every kv_quant scenario) so the
    accounting the fit checker and the benches report can never drift
    from the math admission control sizes pools with."""
    return depth * layout.reserved_rows() * kv_row_bytes(
        hkv, head_dim, layout.kv_quant, compute_itemsize)


class DenseLayout:
    """The original fixed-slot layout: each slot statically owns
    ``rows_per_slot`` contiguous KV rows per layer.  Admission never
    waits on memory — capacity IS ``max_slots`` — so the allocator
    surface is trivially permissive.  ``kv_quant`` records the storage
    scenario riding in the device cache (scale leaves live NEXT TO their
    K/V rows, same indexing) so stats and sizing math stay layout-aware.
    """

    name = "dense"

    def __init__(self, max_slots: int, rows_per_slot: int,
                 kv_quant: str = "none"):
        self.max_slots = max_slots
        self.rows_per_slot = rows_per_slot
        self.kv_quant = kv_quant

    def can_admit(self, prompt: Sequence[int], max_new_tokens: int) -> bool:
        return True

    def reserved_rows(self) -> int:
        """KV rows allocated per layer: every slot statically owns its
        full span for the engine's lifetime."""
        return self.max_slots * self.rows_per_slot

    def stats(self) -> dict:
        return {"kv_quant": self.kv_quant}


class BlockPool:
    """Free-list + refcount + prefix-cache bookkeeping for one shared
    pool of KV blocks (block ids ``0..num_blocks-1``, mirrored by every
    layer's device-side pool).

    Block states: **free** (on the free list), **active** (ref > 0,
    owned by ≥ 1 slot), **cached** (ref == 0 but registered under a
    prefix digest — reclaimable: it sits in an LRU and is evicted only
    when the free list runs dry).  ``available()`` counts free + cached
    — what an admission-time reservation can draw on.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"need >= 1 KV block, got {num_blocks}")
        self.num_blocks = num_blocks
        self._free: deque[int] = deque(range(num_blocks))
        self._ref: Dict[int, int] = {}
        self._digest_of: Dict[int, bytes] = {}
        self._by_digest: Dict[bytes, int] = {}
        # reclaimable cached blocks (ref == 0), oldest first
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # ---- queries ----------------------------------------------------------

    def available(self) -> int:
        return len(self._free) + len(self._lru)

    def stats(self) -> dict:
        free, cached = len(self._free), len(self._lru)
        return {
            "kv_blocks_total": self.num_blocks,
            "kv_blocks_free": free,
            "kv_blocks_cached": cached,
            "kv_blocks_active": self.num_blocks - free - cached,
            "prefix_cache_hits": self.hits,
            "prefix_cache_misses": self.misses,
            "prefix_cache_evictions": self.evictions,
        }

    def peek(self, digests: Sequence[bytes]) -> Tuple[int, int]:
        """How far the cache covers ``digests``: ``(hits,
        hits_in_lru)`` — without claiming anything."""
        hits = in_lru = 0
        for d in digests:
            b = self._by_digest.get(d)
            if b is None:
                break
            hits += 1
            if b in self._lru:
                in_lru += 1
        return hits, in_lru

    # ---- transitions ------------------------------------------------------

    def claim(self, digests: Sequence[bytes]) -> List[int]:
        """Take a reference on the longest cached prefix of ``digests``
        and return the claimed block ids (counts hits/misses)."""
        out: List[int] = []
        for d in digests:
            b = self._by_digest.get(d)
            if b is None:
                break
            self._lru.pop(b, None)
            self._ref[b] = self._ref.get(b, 0) + 1
            out.append(b)
        self.hits += len(out)
        self.misses += len(digests) - len(out)
        return out

    def alloc(self) -> int:
        """One fresh block (ref = 1): the free list first, else evict
        the oldest reclaimable cached block.  Raises when the pool is
        truly exhausted — reservations (see :class:`PagedLayout`) are
        supposed to make that unreachable."""
        if self._free:
            b = self._free.popleft()
        elif self._lru:
            b, _ = self._lru.popitem(last=False)
            d = self._digest_of.pop(b)
            self._by_digest.pop(d, None)
            self.evictions += 1
        else:
            raise RuntimeError(
                "KV block pool exhausted — admission reservation failed "
                "to hold blocks back (engine bug)")
        self._ref[b] = 1
        return b

    def register(self, block: int, digest: bytes) -> None:
        """Enter a completed prompt block into the prefix cache.  First
        writer wins: if the digest is already cached under another
        block, the duplicate is simply not registered."""
        if block in self._digest_of or digest in self._by_digest:
            return
        self._digest_of[block] = digest
        self._by_digest[digest] = block

    def release(self, block: int) -> None:
        """Drop one reference; at zero the block returns to the free
        list, or to the reclaimable LRU if it is prefix-cached."""
        n = self._ref.get(block, 0) - 1
        if n > 0:
            self._ref[block] = n
            return
        self._ref.pop(block, None)
        if block in self._digest_of:
            self._lru[block] = None
        else:
            self._free.append(block)


class PagedLayout:
    """Paged block-pool layout: host-side allocator + per-slot page
    bookkeeping, mirroring the device-side int32 page tables the model
    reads (``models/transformer_lm.py`` paged branch).

    ``rows_per_slot`` is the slot's LOGICAL row span (``max_len`` plain,
    ``sinks + window`` windowed) — rounded up to whole blocks it
    becomes ``r_pad = pages_per_slot * block_size``, the per-slot page
    count.  Windowed rings reuse their rows, so a slot can never need
    more than ``pages_per_slot`` blocks no matter how long it decodes.

    **Reservation discipline** (the admission-backpressure fix): every
    admitted slot records the worst-case blocks it may still allocate
    (``promised``).  ``can_admit`` only accepts a request when
    ``available - Σ promised`` covers its own worst case, so an admitted
    request can ALWAYS run to its token budget — block exhaustion shows
    up as queueing/backpressure at admission, never as a stuck active
    slot.
    """

    name = "paged"

    def __init__(self, max_slots: int, rows_per_slot: int, block_size: int,
                 num_blocks: int, prefix_cache: bool = False,
                 kv_quant: str = "none"):
        if block_size < 1:
            raise ValueError(f"kv_block_size must be >= 1, got {block_size}")
        self.max_slots = max_slots
        self.block_size = block_size
        self.rows_per_slot = rows_per_slot
        #: KV storage scenario: scale blocks mirror the K/V pools
        #: ([num_blocks, block_size, hkv] next to each pool) so block
        #: ids index values and scales identically
        self.kv_quant = kv_quant
        self.pages_per_slot = -(-rows_per_slot // block_size)
        self.r_pad = self.pages_per_slot * block_size
        self.prefix_enabled = prefix_cache
        self.pool = BlockPool(num_blocks)
        #: per-slot page → block id (-1 = unbound), a host mirror of the
        #: device page-table rows
        self.slot_pages: List[List[int]] = [
            [-1] * self.pages_per_slot for _ in range(max_slots)]
        self._allocated = [0] * max_slots  # bound page count (prefix incl.)
        self._promised = [0] * max_slots   # worst-case blocks still to bind
        # single-entry digest memo: can_admit re-runs for the SAME queue
        # head every scheduler tick (and admit then register_prompt
        # follow it), so without this each tick re-hashes the whole
        # prompt under the scheduler lock.  Keyed by IDENTITY — the
        # held reference keeps the id from being recycled by another
        # list — so a memo hit is O(1), not O(plen)
        self._memo: Tuple[Optional[Sequence[int]], List[bytes]] = (None, [])

    def _digests(self, tokens: Sequence[int]) -> List[bytes]:
        if self._memo[0] is not tokens:
            self._memo = (tokens, prefix_digests(tokens, self.block_size))
        return self._memo[1]

    # ---- sizing -----------------------------------------------------------

    def pages_for(self, ntokens: int) -> int:
        """Blocks needed to hold ``ntokens`` positions: row reuse caps
        the answer at ``pages_per_slot`` for windowed rings."""
        return -(-min(ntokens, self.r_pad) // self.block_size)

    def reserved_rows(self) -> int:
        """KV rows allocated per layer: the whole shared block pool
        (slots bind pool blocks; nothing is reserved per slot)."""
        return self.pool.num_blocks * self.block_size

    # ---- admission --------------------------------------------------------

    def can_admit(self, prompt: Sequence[int], max_new_tokens: int) -> bool:
        """Would admitting this request keep every already-admitted
        slot's worst case coverable?"""
        need = self.pages_for(len(prompt) + max_new_tokens)
        hits = in_lru = 0
        if self.prefix_enabled:
            hits, in_lru = self.pool.peek(
                self._digests(prompt)
                [: max(0, (len(prompt) - 1) // self.block_size)])
        promised = sum(self._promised)
        return (self.pool.available() - in_lru - promised) >= (need - hits)

    def admit(self, slot: int, prompt: Sequence[int],
              max_new_tokens: int) -> int:
        """Claim cached prefix blocks into ``slot`` and reserve its
        worst case; returns the position prefill starts from (0 when
        nothing was reusable).  The claim is capped so the LAST prompt
        token is always re-prefilled into a fresh block — its logits
        seed the first generated token, and the cap guarantees shared
        blocks are never written to (copy-on-write at the divergence
        block, with the "copy" being a fresh prefill)."""
        plen = len(prompt)
        claimed: List[int] = []
        if self.prefix_enabled:
            cap = max(0, (plen - 1) // self.block_size)
            claimed = self.pool.claim(self._digests(prompt)[:cap])
        pages = self.slot_pages[slot]
        for i, b in enumerate(claimed):
            pages[i] = b
        self._allocated[slot] = len(claimed)
        self._promised[slot] = (
            self.pages_for(plen + max_new_tokens) - len(claimed))
        return len(claimed) * self.block_size

    # ---- growth -----------------------------------------------------------

    def alloc_rows(self, slot: int, nrows: int) -> List[Tuple[int, int]]:
        """Bind fresh blocks so the slot covers ``nrows`` logical rows;
        returns the new ``(page, block)`` bindings for the engine to
        write into the device page tables."""
        target = self.pages_for(nrows)
        binds: List[Tuple[int, int]] = []
        pages = self.slot_pages[slot]
        while self._allocated[slot] < target:
            b = self.pool.alloc()
            page = self._allocated[slot]
            pages[page] = b
            binds.append((page, b))
            self._allocated[slot] += 1
            self._promised[slot] = max(0, self._promised[slot] - 1)
        return binds

    # ---- completion / teardown -------------------------------------------

    def register_prompt(self, slot: int, prompt: Sequence[int]) -> None:
        """After prefill completes, enter every FULL prompt block into
        the prefix cache (full = wholly covered by prompt positions, so
        its K/V can never be touched by this request's decode)."""
        if not self.prefix_enabled:
            return
        pages = self.slot_pages[slot]
        for i, d in enumerate(self._digests(prompt)):
            if pages[i] >= 0:
                self.pool.register(pages[i], d)

    def release(self, slot: int) -> None:
        """Return the slot's blocks to the pool (cached blocks drop a
        reference and stay reclaimable) and clear its reservation."""
        pages = self.slot_pages[slot]
        for i, b in enumerate(pages):
            if b >= 0:
                self.pool.release(b)
            pages[i] = -1
        self._allocated[slot] = 0
        self._promised[slot] = 0

    # ---- reporting --------------------------------------------------------

    def stats(self) -> dict:
        s = self.pool.stats()
        s["kv_blocks_promised"] = sum(self._promised)
        s["kv_quant"] = self.kv_quant
        return s
