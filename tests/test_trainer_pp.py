"""Pipeline parallelism as a first-class trainer mode.

``prepare_training(spmd="pp")`` stages the LM's decoder blocks over the
mesh's ``pipe`` axis via the GPipe schedule; ``spmd="pp_1f1b"`` compiles
the hand-scheduled 1F1B train step (O(S) activation memory) and still
evaluates through the GPipe forward on the same split tree.  Both ride
the full trainer surface: prefetch loader, train loop, evaluate, and
checkpoint resume.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu import mesh as mesh_lib, optim
from fluxdistributed_tpu.data import SyntheticTextDataset
from fluxdistributed_tpu.models.transformer_lm import TransformerLM
from fluxdistributed_tpu.train import prepare_training, train
from fluxdistributed_tpu.train.logging import NullLogger

VOCAB = 32


@pytest.fixture(scope="module")
def pp_mesh():
    return mesh_lib.make_mesh({"data": 2, "pipe": 4})


def _model(vocab: int = VOCAB):
    return TransformerLM(
        vocab=vocab, dim=32, depth=4, num_heads=2, mlp_dim=64,
        dtype=jnp.float32, dropout=0.0,
    )


@pytest.mark.parametrize("spmd", ["pp", "pp_1f1b"])
def test_pp_trainer_mode_trains_and_evaluates(pp_mesh, spmd):
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=24, peak=0.95)
    task = prepare_training(
        _model(), ds, optim.adam(3e-3),
        mesh=pp_mesh, batch_size=16, cycles=30, topk=(),
        spmd=spmd, num_microbatches=4,
    )
    losses = []
    for batch in task.loader:
        task.state, m = task.step_fn(task.state, batch)
        losses.append(float(m["loss"]))
    # learns the Markov chain: from ~ln(32)=3.47 well below uniform
    assert losses[0] > 2.5 and losses[-1] < losses[0] * 0.7, (
        losses[0], losses[-1])
    assert int(task.state.step) == 30
    # eval rides the GPipe forward on the same split tree
    loss, metrics = task.eval_fn(
        task.state, next(iter_batches(task, ds)))
    assert np.isfinite(float(loss))


def iter_batches(task, ds):
    from fluxdistributed_tpu import sharding as sharding_lib

    rng = np.random.default_rng(123)
    while True:
        toks = ds.batch(rng, 16)
        yield sharding_lib.shard_batch({"tokens": np.asarray(toks)}, task.mesh)


def test_pp_trainer_checkpoint_resume(pp_mesh, tmp_path):
    from fluxdistributed_tpu.train import restore_training
    from fluxdistributed_tpu.train.checkpoint import save_checkpoint

    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=24, peak=0.95)

    def make_task(cycles):
        return prepare_training(
            _model(), ds, optim.adam(3e-3),
            mesh=pp_mesh, batch_size=16, cycles=cycles, topk=(),
            spmd="pp_1f1b", num_microbatches=4, seed=7,
        )

    task = make_task(5)
    train(task, print_every=0, eval_every=0, logger=NullLogger())
    assert int(task.state.step) == 5
    save_checkpoint(task.state, str(tmp_path), step=5)

    task2 = restore_training(make_task(5), str(tmp_path))
    assert int(task2.state.step) == 5
    train(task2, print_every=0, eval_every=0, logger=NullLogger())
    assert int(task2.state.step) == 10


def test_pp_val_slice_and_evaluate_round_to_microbatch_quantum(pp_mesh, tmp_path):
    """A val slice or evaluate batch that is data-axis divisible but NOT
    microbatch divisible must be rounded by the trainer, not crash the
    compiled pipeline eval (quantum = data_size x M = 8 here)."""
    from fluxdistributed_tpu.data import ByteTextDataset
    from fluxdistributed_tpu.train import evaluate

    p = tmp_path / "corpus.txt"
    p.write_bytes(bytes(range(256)) * 13)  # 3328 bytes -> 138 windows of 24
    ds = ByteTextDataset(str(p), seqlen=24)
    task = prepare_training(
        _model(vocab=256), ds, optim.adam(1e-3),
        mesh=pp_mesh, batch_size=16, cycles=1, topk=(),
        spmd="pp", num_microbatches=4,
        val_dataset=ds, val_samples=6,  # NOT a multiple of quantum 8
    )
    # val slice was rounded UP to one quantum and eval compiles/runs
    assert task.val_batch["tokens"].shape[0] == 8
    loss, _ = task.eval_fn(task.state, task.val_batch)
    assert np.isfinite(float(loss))
    # whole-dataset evaluation rounds its batches the same way
    out = evaluate(task, ds, batch_size=30, topk=())  # rounds down to 24
    assert np.isfinite(out["loss"])
    assert out["samples"] % 8 == 0 and out["samples"] > 0


def test_pp_mode_rejects_bad_configs(pp_mesh):
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=24)
    with pytest.raises(ValueError, match="TransformerLM only"):
        from fluxdistributed_tpu.models import SimpleCNN

        prepare_training(
            SimpleCNN(num_classes=10), ds, optim.adam(1e-3),
            mesh=pp_mesh, batch_size=16, spmd="pp",
            input_shape=(24, 24, 3),
        )
    with pytest.raises(ValueError, match="data.*pipe|pipe.*data"):
        prepare_training(
            _model(), ds, optim.adam(1e-3),
            mesh=mesh_lib.data_mesh(8), batch_size=16, spmd="pp", topk=(),
        )
    with pytest.raises(ValueError, match="microbatches"):
        prepare_training(
            _model(), ds, optim.adam(1e-3),
            mesh=pp_mesh, batch_size=16, spmd="pp", topk=(),
            num_microbatches=3,  # 8 per row not divisible by 3
        )
    with pytest.raises(ValueError, match="loss_fn override"):
        from fluxdistributed_tpu.models import lm_loss_fn

        m = _model()
        prepare_training(
            m, ds, optim.adam(1e-3),
            mesh=pp_mesh, batch_size=16, spmd="pp", topk=(),
            loss_fn=lm_loss_fn(m),
        )
    with pytest.raises(ValueError, match="num_microbatches requires"):
        prepare_training(
            _model(), ds, optim.adam(1e-3),
            mesh=pp_mesh, batch_size=16, spmd="jit", topk=(),
            num_microbatches=8,
        )


def test_pp_1f1b_interleaved_trainer_mode(pp_mesh):
    """pipeline_interleave=True: depth 8 on pipe 4 -> V=2 round-robin
    chunks; trains through the full trainer surface and evals via the
    1F1B program itself (the GPipe forward cannot read that layout)."""
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=24, peak=0.95)
    model = TransformerLM(
        vocab=VOCAB, dim=32, depth=8, num_heads=2, mlp_dim=64,
        dtype=jnp.float32, dropout=0.0,
    )
    task = prepare_training(
        model, ds, optim.adam(3e-3),
        mesh=pp_mesh, batch_size=16, cycles=20, topk=(),
        spmd="pp_1f1b", num_microbatches=4, pipeline_interleave=True,
        val_dataset=ds, val_samples=8,
    )
    losses = []
    for batch in task.loader:
        task.state, m = task.step_fn(task.state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
    loss, metrics = task.eval_fn(task.state, task.val_batch)
    assert np.isfinite(float(loss)) and metrics == {}
    with pytest.raises(ValueError, match="pipeline_interleave requires"):
        prepare_training(
            model, ds, optim.adam(1e-3), mesh=pp_mesh, batch_size=16,
            spmd="pp", topk=(), pipeline_interleave=True,
        )


def test_pp_mode_coerces_image_topk_away(pp_mesh):
    """The default image topk=(1,5,10) can never apply to the LM
    pipeline; prepare_training forces loss-only eval instead of
    crashing at the first eval cadence."""
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=24)
    task = prepare_training(
        _model(), ds, optim.adam(1e-3),
        mesh=pp_mesh, batch_size=16, cycles=1, spmd="pp",
        num_microbatches=4, val_dataset=ds, val_samples=8,
    )  # note: default topk
    loss, metrics = task.eval_fn(task.state, task.val_batch)
    assert np.isfinite(float(loss)) and metrics == {}
