"""FDT104 negative: immutable globals and function-local tables."""
import jax

SCALES = (0.1, 0.2)  # tuple: immutable, snapshot is the value forever


@jax.jit
def scaled(x):
    return x * SCALES[0]


@jax.jit
def local_table(x):
    table = {"lr": 0.1}  # local — rebuilt every trace, no stale capture
    return x * table["lr"]


REGISTRY = {}  # mutable, but only host code touches it


def register(name, fn):
    REGISTRY[name] = fn
