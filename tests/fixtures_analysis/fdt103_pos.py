"""FDT103 positive: weak-typed scalar literals in traced code."""
import jax
import jax.numpy as jnp


@jax.jit
def scaled(x):
    return x * jnp.array(1.5)  # weak f32/f64 — promotion depends on x


@jax.jit
def shifted(x):
    return x + jnp.asarray(-3)  # weak int
