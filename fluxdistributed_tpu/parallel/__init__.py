from . import multihost
from .collectives import pmean, psum, all_gather, reduce_scatter, ppermute_ring
from .dp import TrainState, make_train_step, make_eval_step, make_train_step_shardmap

__all__ = [
    "multihost",
    "pmean",
    "psum",
    "all_gather",
    "reduce_scatter",
    "ppermute_ring",
    "TrainState",
    "make_train_step",
    "make_eval_step",
    "make_train_step_shardmap",
]
