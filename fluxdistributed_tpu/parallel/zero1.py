"""ZeRO-1 cross-replica weight-update sharding for the DP path.

Plain DP (``dp.make_train_step``) replicates the ``TrainState``: every
replica all-reduces the full gradient and then applies the IDENTICAL
full-model optimizer update — N devices burn memory and FLOPs on the
same Adam step (the redundancy the reference's per-device ``update``
loop has, src/ddp_tasks.jl:163-172).  "Automatic Cross-Replica Sharding
of Weight Update in Data-Parallel Training" (Xu et al., arXiv:2004.13336)
removes it without touching the model's parallelism:

1. **reduce-scatter** the gradients — each replica receives the SUM of
   one 1/N slice (half the wire bytes of the all-reduce it replaces),
2. apply the optimizer to that slice only — optimizer state lives
   sharded 1/N per device, update FLOPs drop N×,
3. **all-gather** the updated parameter slices back to replicated.

Numerics are identical to DP: the same summed gradient reaches the same
elementwise update, only *where* each element is updated changes.

Sharding is on the **flattened** leaf: each parameter/gradient leaf is
raveled to 1-D and zero-padded to a multiple of the data-axis size, so
ANY leaf shape shards evenly (contrast ``fsdp.fsdp_leaf_spec``, which
must hunt for a divisible dimension and leaves indivisible leaves
replicated).  Optimizer state mirrors that layout — flat padded leaves,
nested per-param exactly like the unsharded state (momentum/Adam slots
keep their tuple/dict structure), so the TP/PP state-spec machinery and
orbax checkpointing see a perfectly ordinary state tree whose leaves
happen to be 1-D and sharded.

Two implementations, mirroring ``dp.py``'s pair:

* ``make_train_step_zero1`` — pure GSPMD (default): the optimizer is
  wrapped by ``zero1_optimizer`` to flatten, constrain grads to
  ``P(data)`` (XLA turns the gradient all-reduce into the
  reduce-scatter), update, and constrain the result back to replicated
  (the all-gather) — the schedule is *derived* by the SPMD partitioner
  from annotations, exactly how ``fsdp.py`` gets ZeRO-3.  Composes
  unchanged with ``accum_steps``, ``steps_per_call`` (scan-K),
  ``donate``, and the trainer's OOM-skip because it IS
  ``dp.make_train_step`` with different shardings.
* ``make_train_step_zero1_shardmap`` — explicit collectives
  (``collectives.reduce_scatter`` / ``collectives.all_gather`` inside
  ``shard_map``), the literal schedule of the paper, for the
  explicit-SPMD story and as the base for manual-collective pipelines.
  Elementwise update rules only (each device updates a slice it cannot
  see past — LARS layer norms / global-norm clipping need the GSPMD
  variant, where the partitioner inserts the norm collectives).

Memory: per-device optimizer state drops ~N× on an N-way mesh — for
Adam (two f32 slots) on an f32 model that is the difference between 2×
model size per device and 2×/N.  Params themselves stay replicated
(that is ZeRO-3 / ``fsdp.py``'s job); ZeRO-1 is the sweet spot when
params fit but the optimizer copies hurt, at DP-identical step math.

Usage::

    state, shardings = zero1_state(params, opt, mesh)
    step = make_train_step_zero1(loss_fn, opt, mesh, shardings)
    eval_step = dp.make_eval_step(loss_fn, mesh, state_shardings=shardings)

With ``optim.with_ema`` the shadow params are flat-sharded like every
other slot — read them with :func:`zero1_ema_params` (plain
``optim.ema_params`` would hand back 1-D padded slices).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import mesh as mesh_lib
from ..optim import Optimizer
from . import collectives, dp

__all__ = [
    "zero1_optimizer",
    "zero1_state",
    "zero1_state_shardings",
    "zero1_ema_params",
    "make_train_step_zero1",
    "make_train_step_zero1_shardmap",
    "per_device_state_bytes",
]


def _is_none(x):
    return x is None


def _flatten_leaf(x, nshards: int):
    """Ravel to 1-D and zero-pad to a multiple of ``nshards``.

    Padding zeros are inert through every elementwise rule shipped in
    ``optim``: grad 0 keeps momentum/Adam slots at 0, so the padded tail
    never changes and never contaminates the real entries.  (Norm-based
    rules see the same norms too — zeros contribute nothing.)
    """
    flat = jnp.ravel(x)
    pad = (-flat.size) % nshards
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat


def _flatten_tree(tree, nshards: int):
    return jax.tree.map(
        lambda x: None if x is None else _flatten_leaf(x, nshards),
        tree,
        is_leaf=_is_none,
    )


def _unflatten_like(flat_tree, template):
    """Invert ``_flatten_tree``: drop the pad, restore each leaf's shape."""
    return jax.tree.map(
        lambda f, p: None if p is None else f[: p.size].reshape(p.shape),
        flat_tree,
        template,
        is_leaf=_is_none,
    )


def zero1_optimizer(
    inner: Optimizer, mesh: Mesh, axis: str = mesh_lib.DATA_AXIS
) -> Optimizer:
    """Wrap ``inner`` so its state and update compute shard 1/N over
    ``axis`` (the GSPMD variant).

    ``init`` initializes the inner rule on the FLATTENED-padded param
    tree (state leaves come out flat).  ``update`` constrains the
    flattened gradients to ``P(axis)`` — under ``jit`` that single
    annotation converts the gradient all-reduce into a reduce-scatter
    and shards every downstream update op — then constrains the updated
    flat params back to replicated (the all-gather) and restores leaf
    shapes.  Pure and jit-compatible like every ``optim`` rule.
    """
    n = mesh.shape[axis]
    shard = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())

    def constrain(tree, sh):
        return jax.tree.map(
            lambda x: None if x is None else jax.lax.with_sharding_constraint(x, sh),
            tree,
            is_leaf=_is_none,
        )

    def init(params):
        return inner.init(_flatten_tree(params, n))

    def update(params, grads, state, step):
        flat_p = constrain(_flatten_tree(params, n), shard)
        # the reduce-scatter point: annotating the flat grad P(axis)
        # makes XLA materialize only this device's summed slice
        flat_g = constrain(_flatten_tree(grads, n), shard)
        new_flat_p, new_state = inner.update(flat_p, flat_g, state, step)
        # the all-gather point: the updated slices rejoin as replicated
        new_flat_p = constrain(new_flat_p, repl)
        return _unflatten_like(new_flat_p, params), new_state

    return Optimizer(init, update, name=f"zero1({inner.name})")


def _opt_leaf_spec(x, axis: str, n: int) -> P:
    """P(axis) for leaves whose leading dim splits evenly over the axis
    (every leaf ``zero1_optimizer`` produces); P() otherwise (scalar or
    non-divisible slots a custom rule might carry).  The single rule both
    step variants derive their optimizer-state layout from."""
    shape = np.shape(x)
    divisible = len(shape) >= 1 and shape[0] > 0 and shape[0] % n == 0
    return P(axis) if divisible else P()


def _opt_leaf_sharding(mesh: Mesh, axis: str):
    n = mesh.shape[axis]

    def leaf(x):
        if x is None:
            return None
        return NamedSharding(mesh, _opt_leaf_spec(x, axis, n))

    return leaf


def zero1_state_shardings(
    state: dp.TrainState, mesh: Mesh, axis: str = mesh_lib.DATA_AXIS
) -> dp.TrainState:
    """A ``TrainState`` of ``NamedSharding``s for a ZeRO-1 state: params,
    mutable model state and the step counter replicated; flat optimizer
    state sharded over ``axis`` (any non-divisible or scalar slot —
    none are produced by ``zero1_optimizer``, but custom rules may —
    stays replicated)."""
    repl = NamedSharding(mesh, P())
    return dp.TrainState(
        params=jax.tree.map(lambda _: repl, state.params, is_leaf=_is_none),
        opt_state=jax.tree.map(
            _opt_leaf_sharding(mesh, axis), state.opt_state, is_leaf=_is_none
        ),
        model_state=jax.tree.map(lambda _: repl, state.model_state),
        step=repl,
    )


def zero1_state(
    params,
    optimizer: Optimizer,
    mesh: Mesh,
    axis: str = mesh_lib.DATA_AXIS,
    model_state=None,
) -> tuple[dp.TrainState, dp.TrainState]:
    """Create and place a ZeRO-1 ``TrainState``.

    Returns ``(state, shardings)``: params/model-state replicated,
    optimizer state initialized FLAT by ``zero1_optimizer(optimizer)``
    and distributed 1/N over ``axis``.  Both step variants consume this
    same layout, and orbax checkpoints restore onto it shard-by-shard
    (``load_checkpoint`` takes each target leaf's sharding).
    """
    from ..sharding import unaliased

    z = zero1_optimizer(optimizer, mesh, axis)
    state = dp.TrainState.create(params, z, model_state=model_state)
    shardings = zero1_state_shardings(state, mesh, axis)
    state = jax.tree.map(
        lambda x, s: x if x is None else jax.device_put(unaliased(x), s),
        state,
        shardings,
        is_leaf=_is_none,
    )
    return state, shardings


def make_train_step_zero1(
    loss_fn: Callable,
    optimizer: Optimizer,
    mesh: Mesh,
    shardings: dp.TrainState,
    axis: str = mesh_lib.DATA_AXIS,
    donate: bool = True,
    accum_steps: int = 1,
    seed: int = 0,
    steps_per_call: int = 1,
    guard: bool = False,
):
    """The DP train step with a ZeRO-1 sharded weight update (GSPMD).

    Identical loss/gradient math to ``dp.make_train_step`` — the wrapped
    optimizer changes only the update's data layout, so every DP feature
    (gradient accumulation, the scan-K device loop, donation, OOM-skip
    at the trainer, the ``guard`` anomaly sentinel) composes unchanged.  ``shardings`` is the tree from
    :func:`zero1_state` and is REQUIRED: compiling without it would fall
    back to dp's replicated default, which silently re-replicates the
    optimizer state on the first step — the exact redundancy ZeRO-1
    exists to remove.
    """
    if shardings is None:
        raise ValueError(
            "make_train_step_zero1 needs the sharding tree from "
            "zero1_state(...): without it the state compiles replicated "
            "and the 1/N optimizer-memory saving silently disappears"
        )
    z = zero1_optimizer(optimizer, mesh, axis)
    return dp.make_train_step(
        loss_fn, z, mesh,
        axis=axis, donate=donate, accum_steps=accum_steps, seed=seed,
        state_shardings=shardings, steps_per_call=steps_per_call,
        guard=guard,
    )


def make_train_step_zero1_shardmap(
    loss_fn: Callable,
    optimizer: Optimizer,
    mesh: Mesh,
    state: dp.TrainState,
    axis: str = mesh_lib.DATA_AXIS,
    donate: bool = True,
    seed: int = 0,
):
    """Explicit-collectives ZeRO-1: the paper's schedule, written out.

    Per device inside one ``shard_map``: local gradients on the batch
    shard → ``reduce_scatter`` (each device receives the summed 1/N
    flat slice) → the inner optimizer updates THAT SLICE against its
    local flat param/state slice → ``all_gather`` rebuilds the
    replicated params.  The literal analog of the reference's
    sync-then-update loop with the redundant N-fold update sheared off.

    ``state`` (from :func:`zero1_state`) supplies the optimizer-state
    tree structure for the shard_map specs.  Elementwise update rules
    only: a slice-local update cannot reproduce LARS layer norms or
    global-norm clipping — use the GSPMD variant for those.
    """
    for frag in ("lars", "clip"):
        if frag in optimizer.name:
            raise ValueError(
                f"optimizer {optimizer.name!r} needs cross-slice reductions "
                "(layer/global norms); the shard_map ZeRO-1 variant updates "
                "each 1/N slice locally — use make_train_step_zero1 (GSPMD), "
                "where XLA inserts the norm collectives"
            )
    nshards = mesh.shape[axis]
    with_rng = dp._accepts_rng(loss_fn)
    repl_spec = P()
    shard_spec = P(axis)
    state_specs = dp.TrainState(
        params=jax.tree.map(lambda _: repl_spec, state.params, is_leaf=_is_none),
        # same divisibility rule as zero1_state_shardings, so the specs
        # always agree with how zero1_state placed the leaves
        opt_state=jax.tree.map(
            lambda x: None if x is None else _opt_leaf_spec(x, axis, nshards),
            state.opt_state,
            is_leaf=_is_none,
        ),
        model_state=jax.tree.map(lambda _: repl_spec, state.model_state),
        step=repl_spec,
    )

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(state_specs, shard_spec),
        out_specs=(state_specs, repl_spec),
        check_vma=False,
    )
    def step(state: dp.TrainState, batch):
        def lossf(params):
            if with_rng:
                rng = jax.random.fold_in(
                    jax.random.fold_in(jax.random.PRNGKey(seed), state.step),
                    jax.lax.axis_index(axis),
                )
                return loss_fn(params, state.model_state, batch, True, rng=rng)
            return loss_fn(params, state.model_state, batch, True)

        (loss, (new_mstate, _)), grads = jax.value_and_grad(lossf, has_aux=True)(
            state.params
        )
        loss = jax.lax.pmean(loss, axis)
        new_mstate = collectives.pmean(new_mstate, axis)
        # ZeRO-1 gradient exchange: sum-reduce-scatter the flat padded
        # grads, then mean — each device holds grad slice i of N at half
        # the wire bytes of DP's all-reduce.  (A VMA-era tracer will have
        # already psummed the cotangent of the replicated params; there
        # the scatter degenerates to slicing the local 1/N chunk, which
        # XLA's all-reduce-reassociation folds back into a reduce-scatter.)
        from ..compat import LEGACY_SHARD_MAP

        i = jax.lax.axis_index(axis)

        def local_chunk(tree):
            """Slice i of N from each flat padded leaf."""
            return jax.tree.map(
                lambda x: None if x is None else jax.lax.dynamic_slice_in_dim(
                    x, i * (x.shape[0] // nshards), x.shape[0] // nshards
                ),
                tree,
                is_leaf=_is_none,
            )

        flat_g = _flatten_tree(grads, nshards)
        if LEGACY_SHARD_MAP:
            flat_g = collectives.reduce_scatter(flat_g, axis)
        else:
            flat_g = local_chunk(flat_g)
        flat_g = jax.tree.map(
            lambda g: None if g is None else g / nshards, flat_g, is_leaf=_is_none
        )
        # this device's param slice, matching its optimizer-state slice
        flat_p = local_chunk(_flatten_tree(state.params, nshards))
        new_flat_p, new_opt = optimizer.apply(
            flat_p, flat_g, state.opt_state, state.step
        )
        # rebuild replicated params from the N updated slices
        gathered = collectives.all_gather(new_flat_p, axis)
        new_params = _unflatten_like(gathered, state.params)
        new_state = dp.TrainState(
            params=new_params,
            opt_state=new_opt,
            model_state=new_mstate,
            step=state.step + 1,
        )
        return new_state, {"loss": loss}

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def zero1_ema_params(state: dp.TrainState):
    """The EMA shadow parameters from a ZeRO-1 state whose optimizer is
    ``optim.with_ema(...)``, restored to model shapes.

    Under ZeRO-1 the shadow lives FLAT-padded and data-sharded like every
    other optimizer slot, so ``optim.ema_params`` alone returns 1-D
    padded slices a model cannot consume — this helper unflattens them
    against the state's params.  Evaluate via e.g.
    ``dataclasses.replace(state, params=zero1_ema_params(state))``.
    """
    from ..optim import ema_params

    return _unflatten_like(ema_params(state.opt_state), state.params)


def per_device_state_bytes(tree) -> dict:
    """Addressable bytes of ``tree`` held per device — the accounting
    used to verify the ~N× optimizer-memory saving (tests and the bench
    report both read it).  Returns ``{device: bytes}``."""
    out: dict = {}
    for leaf in jax.tree.leaves(tree):
        if not hasattr(leaf, "addressable_shards"):
            continue
        seen = set()
        for s in leaf.addressable_shards:
            # replicated leaves surface one shard per device; count each
            # device's copy, but a device only once per leaf
            if s.device in seen:
                continue
            seen.add(s.device)
            out[s.device] = out.get(s.device, 0) + s.data.nbytes
    return out
