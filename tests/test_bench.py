"""Guard the driver-facing benchmark harness.

bench.py is the artifact the round driver executes on real hardware; a
breakage there records a failed round, so its construction path and
always-emit-JSON contract get CI coverage on the fake mesh.
"""

from __future__ import annotations

import json
import os
import sys

import pytest

# tier-2 (slow): bench-harness subprocess runs — the tier-1 iteration loop must fit the
# 870s verify window (ROADMAP); CI's slow job still runs this file
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bench_mod():
    import pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    import bench

    return bench


def test_build_step_runs_one_step(bench_mod):
    step, state, b = bench_mod.build_step(batch=8, size=32)
    state2, m = step(state, b)
    assert float(m["loss"]) > 0
    assert int(state2.step) == 1


def test_fused_steps_advance_state(bench_mod):
    """fuse=k runs k optimizer steps per call (one dispatch), same
    (state, metrics) signature as the plain step."""
    step, state, b = bench_mod.build_step(batch=8, size=32, fuse=4)
    state2, m = step(state, b)
    assert int(state2.step) == 4
    assert float(m["loss"]) > 0
    state3, _ = step(state2, b)
    assert int(state3.step) == 8


def test_step_flops_and_mfu(bench_mod):
    """Cost analysis counts a sane FLOP total WITHOUT a second compile;
    mfu_pct is None on CPU (unknown peak) and arithmetic on a known one."""
    step, state, b = bench_mod.build_step(batch=8, size=32, donate=False)
    fl = bench_mod.step_flops(step, state, b)
    # ResNet-50 fwd+bwd at 32x32 is ~0.25 GFLOP/img -> total well over 1e8
    assert fl > 1e8, fl
    assert bench_mod.mfu_pct(fl, dt=0.01, nchips=8) is None  # cpu device_kind
    # direct arithmetic check against a fake peak table entry
    bench_mod._PEAK_BF16_TFLOPS["cpu"] = 1.0  # device_kind == "cpu" on host
    try:
        got = bench_mod.mfu_pct(1e10, dt=0.1, nchips=1)
        assert got == 10.0, got  # 1e10/0.1 = 1e11 FLOP/s = 10% of 1 TFLOP/s
    finally:
        bench_mod._PEAK_BF16_TFLOPS.pop("cpu")


def test_build_step_variant_knobs(bench_mod):
    import jax.numpy as jnp

    step, state, b = bench_mod.build_step(
        batch=8, size=32, donate=False, accum_steps=2,
        norm_dtype=jnp.float32, input_f32=True,
    )
    _, m = step(state, b)
    assert float(m["loss"]) > 0
    assert b["image"].dtype == jnp.float32

    step, state, b = bench_mod.build_step(batch=8, size=32, donate=False, remat=True)
    _, m = step(state, b)
    assert float(m["loss"]) > 0

    step, state, b = bench_mod.build_step(batch=8, size=32, donate=False, s2d=True)
    assert b["image"].shape == (8, 16, 16, 12)  # host-side re-layout fed
    _, m = step(state, b)
    assert float(m["loss"]) > 0


def test_main_emits_error_json_and_rc0_on_failure(bench_mod, monkeypatch, capsys):
    """main() must print the JSON line and return normally no matter how
    the measurement subprocess dies — crash, hang (TimeoutExpired), or
    garbage output (the 2026-07-30 unavailable-backend scenario)."""
    import subprocess

    def boom(*a, **kw):
        raise subprocess.TimeoutExpired(cmd="bench", timeout=1)

    monkeypatch.setattr(subprocess, "run", boom)
    monkeypatch.setattr(bench_mod.time, "sleep", lambda s: None)
    bench_mod.main()  # must not raise
    line = capsys.readouterr().out.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["unit"] == "images/sec/chip"
    assert "timed out" in out["error"]
    # the cold-start ledger rides the ERROR json too, so a timed-out
    # round says whether the window went to compilation or the hardware
    # (no child ran here, so the forensic defaults apply)
    assert out["phase"] == "unknown"
    assert out["compile_seconds"] == 0.0
    assert out["cache_hits"] == 0 and out["cache_misses"] == 0
    # the static-health stamp rides the error JSON too: a zero artifact
    # still records whether the code it ran was lint-clean (shape only —
    # repo lint cleanliness is bin/lint.py --check's gate, and WIP code
    # with a finding must not fail an unrelated bench test)
    assert {"findings", "new", "by_rule"} <= set(out["lint"])
    # the robustness stamp rides the error JSON too: a dead round
    # records the fault/watchdog/guard counters it saw (or that it saw
    # none — the stamp is never absent)
    assert isinstance(out["guard"], dict)
    # the memory stamp rides the error JSON too: a dead round records
    # the HBM state at death ({"available": false} here — CPU has no
    # memory_stats, the None-safe degradation, never a crash)
    assert out["memory"] == {"available": False}

    class FakeDone:
        returncode = 1
        stdout = "not json\nalso not json"
        stderr = "injected failure"

    monkeypatch.setattr(subprocess, "run", lambda *a, **kw: FakeDone())
    bench_mod.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    out = json.loads(line)
    assert "injected failure" in out["error"]

    class FakeOK:
        returncode = 0
        stdout = 'preamble\n{"metric": "m", "value": 1.0, "unit": "images/sec/chip", "vs_baseline": 1.0}'
        stderr = ""

    monkeypatch.setattr(subprocess, "run", lambda *a, **kw: FakeOK())
    bench_mod.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line)["value"] == 1.0


def test_status_file_snapshots_phase_and_compile_ledger(bench_mod, tmp_path):
    """The bounded subprocess drops phase + compile-counter snapshots;
    main() folds the last one into the error JSON on a dead attempt."""
    path = str(tmp_path / "status.json")
    bench_mod._write_status(path, "compile")
    snap = json.loads(open(path).read())
    assert snap["phase"] == "compile"
    for key in ("compile_seconds", "cache_hits", "cache_misses"):
        assert key in snap
    # the memory stamp relays through the child status file like the
    # guard stamp — dead hw rounds record memory state at death
    assert snap["memory"] == {"available": False}
    bench_mod._write_status(None, "ignored")  # disabled path: no raise


def test_memory_stamp_static_bytes(bench_mod):
    """memory_stamp(state): live HBM summary (unavailable on CPU) plus
    the exact static bytes of the bench state when it is at hand."""
    import jax.numpy as jnp

    class S:
        params = {"w": jnp.zeros((4, 4), jnp.float32)}
        opt_state = {"m": jnp.zeros((4, 4), jnp.float32)}
        model_state = {}

    out = bench_mod.memory_stamp(S())
    assert out["available"] is False
    assert out["static"]["param_bytes"] == 64
    assert out["static"]["total_bytes"] == 128
    assert "static" not in bench_mod.memory_stamp()


def _tiny_build_step(batch, **kw):
    """A stand-in for build_step so the resumable state machine is
    testable in seconds: same (step, state, batch) contract, trivial
    compile."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def step(state, b):
        s = state + b["image"].sum()
        return s, {"loss": s}

    return step, jnp.zeros(()), {"image": np.ones((batch, 2), np.float32)}


def test_resumable_warm_then_measure(bench_mod, tmp_path, monkeypatch, capsys):
    """Attempt N warms (AOT serialized, ledger advances to 'warmed'),
    attempt N+1 loads the executable and emits a real number with
    attempts/interrupted_at provenance."""
    monkeypatch.setattr(bench_mod, "build_step", _tiny_build_step)
    monkeypatch.setattr(bench_mod, "step_flops", lambda *a: 0.0)
    monkeypatch.setenv("FDTPU_COMPILE_CACHE_DIR", "")  # no cache dir churn
    monkeypatch.setenv("FDTPU_AOT_DIR", str(tmp_path / "aot"))
    ledger = str(tmp_path / "ledger.json")

    # a huge measure margin forces the warm-only outcome (models a
    # budget that only covers the cold half)
    rc = bench_mod.resumable_main(
        ["--ledger", ledger, "--budget", "300", "--steps", "2",
         "--measure-margin", "1e9"])
    assert rc == 0
    warmed = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert warmed["warmed"] is True and warmed["value"] == 0.0
    assert warmed["resumable"]["state"] == "warmed"
    assert warmed["resumable"]["attempts"] == 1
    assert any(f.startswith("bench_step-")
               for f in os.listdir(tmp_path / "aot"))

    rc = bench_mod.resumable_main(
        ["--ledger", ledger, "--budget", "300", "--steps", "2"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] > 0
    assert out["aot_loaded"] is True, "attempt 2 must SKIP the compile"
    assert out["measure_steps"] == 2
    assert out["resumable"] == {
        "attempts": 2, "interrupted_at": None, "state": "measured",
        "ledger": ledger}


def test_resumable_error_json_classifies_retryable(
        bench_mod, tmp_path, monkeypatch, capsys):
    """A code failure in the build phase emits retryable: false (the
    watcher stops); a backend-unavailable failure emits retryable: true
    (the watcher backs off and retries)."""
    from fluxdistributed_tpu import faults

    ledger = str(tmp_path / "ledger.json")

    def broken(batch, **kw):
        raise TypeError("injected code bug")

    monkeypatch.setattr(bench_mod, "build_step", broken)
    rc = bench_mod.resumable_main(["--ledger", ledger, "--budget", "60"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 0.0
    assert out["phase"] == "build"
    assert out["retryable"] is False
    assert out["resumable"]["interrupted_at"] == "build"

    # simulated backend-unavailable on init: the acquire_backend
    # retries are exhausted by the plan, and the death is retryable
    faults.install_plan(faults.FaultPlan().backend_unavailable(99))
    try:
        rc = bench_mod.resumable_main(
            ["--ledger", str(tmp_path / "l2.json"), "--budget", "10"])
    finally:
        faults.clear_plan()
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["phase"] == "backend_init"
    assert out["retryable"] is True


def test_main_error_json_carries_retryable(bench_mod, monkeypatch, capsys):
    """The classic bounded-subprocess path classifies its error JSON
    too, so hw_watch.sh can gate its backoff on it."""
    import subprocess

    def boom(*a, **kw):
        raise subprocess.TimeoutExpired(cmd="bench", timeout=1)

    monkeypatch.setattr(subprocess, "run", boom)
    monkeypatch.setattr(bench_mod.time, "sleep", lambda s: None)
    bench_mod.main()
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # a timeout with no phase marker died in backend territory
    assert out["retryable"] is True


def test_default_cache_dir_env_override(bench_mod, monkeypatch):
    """FDTPU_COMPILE_CACHE_DIR overrides the benchmarks/hw default;
    empty string disables caching entirely."""
    import os

    monkeypatch.delenv("FDTPU_COMPILE_CACHE_DIR", raising=False)
    assert bench_mod.default_cache_dir().endswith(
        os.path.join("benchmarks", "hw", "xla_cache"))
    monkeypatch.setenv("FDTPU_COMPILE_CACHE_DIR", "/somewhere/else")
    assert bench_mod.default_cache_dir() == "/somewhere/else"
    monkeypatch.setenv("FDTPU_COMPILE_CACHE_DIR", "")
    assert bench_mod.default_cache_dir() is None
