"""Data-parallel training steps — the reference's core feature, compiled.

Replaces the reference's task-DDP hot path (SURVEY §3.2): where the
reference spawns one Julia Task per GPU for ``train_step`` (Zygote
gradient + DtoD push into a HOST-resident buffer, src/ddp_tasks.jl:80-84),
barriers, hub-reduces (``sync_buffer`` :93-109), and runs one replicated
optimizer step per device (``update`` :163-172), here the whole
step — forward, backward, gradient all-reduce, optimizer update — is ONE
jitted SPMD program over a ``jax.sharding.Mesh``:

* parameters/optimizer state are *replicated* (NamedSharding ``P()``),
* the batch is *sharded* on the ``data`` axis (``P('data')``),
* the loss is a mean over the global batch, so XLA's gradient of that
  mean IS the cross-replica all-reduce — no buffers, no barriers, no
  hub, and the update is computed once and identical on every device
  (the property the reference asserts via ``ensure_synced``
  src/ddp_tasks.jl:115-126 and its replica-identity tests).

Two implementations are provided:

* ``make_train_step`` — idiomatic ``jit`` with sharding annotations
  (production path; XLA inserts collectives).
* ``make_train_step_shardmap`` — explicit per-device SPMD via
  ``shard_map`` + ``pmean`` (the literal analog of the reference's
  per-replica semantics; also the base for pipelines that need manual
  collectives).  Results are numerically identical; tests assert both
  match single-device global-batch training.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import mesh as mesh_lib
from .. import tree as tree_lib
from ..optim import Optimizer
from . import collectives

Pytree = Any

__all__ = ["TrainState", "guard_sentinel", "make_train_step",
           "make_eval_step", "make_train_step_shardmap"]


@struct.dataclass
class TrainState:
    """Replicated training state: params + optimizer state + mutable model
    state (e.g. BatchNorm running stats) + step counter.

    The analog of the reference's per-device ``(dev, model)`` pairs plus
    ``sts[dev]`` optimizer states (src/ddp_tasks.jl:273-276) — except
    there is exactly one logical copy, kept replicated by sharding.
    """

    params: Pytree
    opt_state: Pytree
    model_state: Pytree
    step: jnp.ndarray

    @classmethod
    def create(cls, params, optimizer: Optimizer, model_state=None):
        return cls(
            params=params,
            opt_state=optimizer.init(params),
            model_state=model_state if model_state is not None else {},
            step=jnp.zeros((), jnp.int32),
        )


# A loss function has signature
#   loss_fn(params, model_state, batch, train: bool, rng=None)
#       -> (loss, (new_model_state, aux))
# where ``batch`` is any pytree of arrays with a leading batch dim and
# ``rng`` (optional keyword) seeds stochastic layers (dropout/drop-path).
# Four-argument custom loss functions remain supported — the step makers
# only pass ``rng`` when the signature accepts it (``_accepts_rng``).


def _accepts_rng(loss_fn: Callable) -> bool:
    import inspect

    try:
        sig = inspect.signature(loss_fn)
    except (TypeError, ValueError):
        return False
    p = sig.parameters.get("rng")
    return p is not None or any(
        q.kind is inspect.Parameter.VAR_KEYWORD for q in sig.parameters.values()
    )


def flax_loss_fn(model, loss, has_aux_state: bool = True) -> Callable:
    """Adapt a flax.linen module + a loss (e.g. ``logitcrossentropy``) to
    the framework's loss signature.  Handles mutable collections such as
    ``batch_stats`` (BatchNorm running statistics) and stochastic layers
    (``rng`` becomes the ``dropout`` stream, e.g. ViT dropout and
    ConvNeXt stochastic depth)."""

    def fn(params, model_state, batch, train: bool, rng=None):
        x, y = batch["image"], batch["label"]
        variables = {"params": params, **model_state}
        rngs = {"dropout": rng} if (train and rng is not None) else None
        if train and model_state:
            out, mutated = model.apply(
                variables, x, train=True, mutable=list(model_state.keys()), rngs=rngs
            )
            return loss(out, y), (mutated, out)
        out = model.apply(variables, x, train=train, rngs=rngs)
        return loss(out, y), (model_state, out)

    return fn


def guard_sentinel(loss, grads):
    """The in-graph anomaly sentinel (``train/guard.py``): a length-2
    f32 vector ``[poisoned_loss, grad_norm]`` computed where the
    gradients already live, so detecting a bad step costs ONE extra
    device->host scalar fetch and zero extra compiles.

    * ``grad_norm`` — global L2 norm over every gradient leaf (f32
      accumulation).  A NaN anywhere poisons it to NaN; an Inf (or an
      f32-overflowing explosion) drives it to Inf — the global
      ``isfinite`` any-reduce over the gradients, folded into a number
      that is also the magnitude signal.
    * ``poisoned_loss`` — the step loss plus ``0 * grad_norm``: equal
      to the loss bit-for-bit when the gradients are finite (the
      loss-spike detector's input), NaN whenever loss or any gradient
      is not (``0 * inf`` and ``0 * nan`` are both NaN) — loss AND
      gradient finiteness any-reduced into one scalar.
    """
    gsq = jnp.float32(0.0)
    for g in jax.tree.leaves(grads):
        gsq = gsq + jnp.sum(jnp.square(g.astype(jnp.float32)))
    gnorm = jnp.sqrt(gsq)
    return jnp.stack(
        [jnp.asarray(loss, jnp.float32) + 0.0 * gnorm, gnorm])


def make_train_step(
    loss_fn: Callable,
    optimizer: Optimizer,
    mesh: Mesh,
    axis: str = mesh_lib.DATA_AXIS,
    donate: bool = True,
    accum_steps: int = 1,
    seed: int = 0,
    state_shardings=None,
    steps_per_call: int = 1,
    guard: bool = False,
):
    """Compile the full DP training step under ``jit`` + shardings.

    Returns ``step_fn(state, batch) -> (state, metrics)`` where ``batch``
    arrays are sharded on ``axis`` and ``state`` is replicated.  The
    gradient all-reduce is implicit in differentiating the global-batch
    mean loss.

    ``state_shardings`` (a ``TrainState`` of ``NamedSharding`` leaves)
    overrides the replicated default for the train state — this is how
    ``fsdp.make_train_step_fsdp`` turns the same step into ZeRO-style
    fully-sharded data parallelism without duplicating the step logic:
    XLA inserts the all-gathers (params on use) and reduce-scatters
    (grads at the sharded update) implied by the annotations.

    ``accum_steps > 1`` enables gradient accumulation (beyond the
    reference, which has no analog): the batch's leading dim is split
    into ``accum_steps`` microbatches processed by a ``lax.scan`` —
    activations for only ONE microbatch are live at a time, so the same
    device memory trains an ``accum_steps``× larger effective batch.
    Gradients are averaged over microbatches (identical semantics to one
    big batch for mean losses); mutable model state (BatchNorm stats)
    threads through the scan sequentially.

    ``seed`` roots the dropout/drop-path stream: two seeds draw different
    masks, the same seed reproduces a run exactly.

    ``steps_per_call > 1`` runs K optimizer steps per dispatch — the
    device loop: the returned function takes batches STACKED on a new
    leading dim ``[K, batch, ...]`` (sharded ``P(None, axis)``, the
    loader's ``chunk=K`` layout) and ``lax.scan``s the step over them,
    returning metrics stacked ``[K]``.  Each step consumes a DIFFERENT
    batch — semantics identical to K separate calls — but the host pays
    one dispatch instead of K, which matters when dispatch crosses a
    network tunnel or the host is slow relative to the step.

    ``guard=True`` adds ``metrics["guard"]`` — the
    :func:`guard_sentinel` ``[poisoned_loss, grad_norm]`` vector (per
    step; stacked ``[K, 2]`` under the device loop), computed in-graph
    from the same gradients the update consumes.  It changes nothing
    about the update math; the trainer's guard policy engine fetches it
    once per step to detect non-finite grads/loss and loss spikes.
    """
    from ..sharding import batch_entry

    repl = NamedSharding(mesh, P())
    # axis=None: batch replicated (e.g. a pure 'expert' mesh where the
    # MoE shard_map does its own token split); a tuple shards the batch
    # dim over several axes jointly (the 3-D (data, fsdp) layouts)
    shard = NamedSharding(mesh, P(batch_entry(axis)) if axis is not None
                          else P())
    state_sh = repl if state_shardings is None else state_shardings
    with_rng = _accepts_rng(loss_fn)

    def grad_of(params, mstate, batch, step_idx):
        def lossf(p):
            if with_rng:
                # per-step dropout/drop-path stream rooted at the user
                # seed, identical on every device (replicated state.step
                # → replicated key)
                rng = jax.random.fold_in(jax.random.PRNGKey(seed), step_idx)
                return loss_fn(p, mstate, batch, True, rng=rng)
            return loss_fn(p, mstate, batch, True)

        return jax.value_and_grad(lossf, has_aux=True)(params)

    def step(state: TrainState, batch):
        if accum_steps == 1:
            (loss, (new_mstate, _)), grads = grad_of(
                state.params, state.model_state, batch, state.step
            )
        else:
            micro = jax.tree.map(
                lambda x: x.reshape(accum_steps, x.shape[0] // accum_steps, *x.shape[1:]),
                batch,
            )

            def body(carry, mb):
                mstate, gsum, lsum, i = carry
                (l, (mstate, _)), g = grad_of(
                    state.params, mstate, mb, state.step * accum_steps + i
                )
                gsum = jax.tree.map(jnp.add, gsum, g)
                return (mstate, gsum, lsum + l, i + 1), None

            gzero = jax.tree.map(jnp.zeros_like, state.params)
            (new_mstate, gsum, lsum, _), _ = jax.lax.scan(
                body, (state.model_state, gzero, 0.0, 0), micro
            )
            grads = jax.tree.map(lambda g: g / accum_steps, gsum)
            loss = lsum / accum_steps
        new_params, new_opt = optimizer.apply(
            state.params, grads, state.opt_state, state.step
        )
        new_state = TrainState(
            params=new_params,
            opt_state=new_opt,
            model_state=new_mstate,
            step=state.step + 1,
        )
        metrics = {"loss": loss}
        if guard:
            metrics["guard"] = guard_sentinel(loss, grads)
        return new_state, metrics

    if steps_per_call == 1:
        return jax.jit(
            step,
            in_shardings=(state_sh, shard),
            out_shardings=(state_sh, repl),
            donate_argnums=(0,) if donate else (),
        )

    if steps_per_call < 1:
        raise ValueError(f"steps_per_call must be >= 1, got {steps_per_call}")
    chunk_shard = NamedSharding(
        mesh, P(None, batch_entry(axis)) if axis is not None else P())

    def chunked(state: TrainState, batches):
        return jax.lax.scan(step, state, batches)

    return jax.jit(
        chunked,
        in_shardings=(state_sh, chunk_shard),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,) if donate else (),
    )


def make_eval_step(
    loss_fn: Callable,
    mesh: Mesh,
    axis: str = mesh_lib.DATA_AXIS,
    topk: tuple = (1, 5, 10),
    state_shardings=None,
):
    """Compiled eval pass returning ``(loss, metrics)``.

    The analog of ``log_loss_and_acc`` (src/ddp_tasks.jl:128-148), but
    where the reference runs TWO forward passes and pulls the logits to
    host for a partial-sort top-k (``topkaccuracy`` src/utils.jl:39-45),
    here one compiled pass computes loss AND top-k accuracies in-graph
    (``lax.top_k`` on device).  Outputs are replicated scalars, so this
    works unchanged on a multi-host mesh where per-shard logits are not
    host-addressable.
    """
    from ..ops import topkaccuracy
    from ..sharding import batch_entry

    repl = NamedSharding(mesh, P())
    # axis=None: batch replicated (e.g. a pure 'expert' mesh where the
    # MoE shard_map does its own token split); tuples shard jointly
    shard = NamedSharding(mesh, P(batch_entry(axis)) if axis is not None
                          else P())
    state_sh = repl if state_shardings is None else state_shardings

    def step(state: TrainState, batch):
        loss, (_, logits) = loss_fn(state.params, state.model_state, batch, False)
        metrics = {
            f"top{k}": topkaccuracy(logits, batch["label"], k=k) for k in topk
        }
        return loss, metrics

    return jax.jit(step, in_shardings=(state_sh, shard), out_shardings=(repl, repl))


def make_train_step_shardmap(
    loss_fn: Callable,
    optimizer: Optimizer,
    mesh: Mesh,
    axis: str = mesh_lib.DATA_AXIS,
    donate: bool = True,
    seed: int = 0,
):
    """Explicit-SPMD DP step: per-device gradients + ``pmean``.

    The literal translation of the reference's semantics — each replica
    computes gradients on its shard (``train_step`` src/ddp_tasks.jl:80-84),
    gradients are mean-reduced across replicas (``sync_buffer`` :93-109 →
    here one ``pmean`` collective), and every replica applies the same
    optimizer update (``update`` :163-172).  Because the averaged gradient
    and the update are computed identically on every device, replicas stay
    bit-identical — the invariant the reference tests
    (test/single_device.jl:160-167).
    """
    repl_spec = P()
    batch_spec = P(axis)
    nshards = mesh.shape[axis]
    with_rng = _accepts_rng(loss_fn)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(repl_spec, batch_spec),
        out_specs=(repl_spec, repl_spec),
    )
    def step(state: TrainState, batch):
        def lossf(params):
            if with_rng:
                # distinct stream per device so each batch shard draws
                # independent dropout/drop-path masks, rooted at the
                # user seed
                rng = jax.random.fold_in(
                    jax.random.fold_in(jax.random.PRNGKey(seed), state.step),
                    jax.lax.axis_index(axis),
                )
                return loss_fn(params, state.model_state, batch, True, rng=rng)
            return loss_fn(params, state.model_state, batch, True)

        (loss, (new_mstate, _)), grads = jax.value_and_grad(lossf, has_aux=True)(
            state.params
        )
        # Differentiating w.r.t. the replicated (P()) params already
        # psums the cotangent across the mesh axis (the transpose of
        # replication); the reference's mean semantics
        # (sync_buffer's divide-by-N, src/ddp_tasks.jl:103-106) is then
        # a division by the shard count, not another collective.  A
        # pre-VMA shard_map tracer inserts NO such psum, so there the
        # mean is one explicit collective instead.
        from ..compat import LEGACY_SHARD_MAP

        if LEGACY_SHARD_MAP:
            grads = collectives.pmean(grads, axis)
        else:
            grads = tree_lib.div(grads, nshards)
        loss = jax.lax.pmean(loss, axis)
        # Mutable model state (BatchNorm running stats) is per-shard →
        # average it across replicas so replicas stay identical.
        new_mstate = collectives.pmean(new_mstate, axis)
        new_params, new_opt = optimizer.apply(
            state.params, grads, state.opt_state, state.step
        )
        new_state = TrainState(
            params=new_params,
            opt_state=new_opt,
            model_state=new_mstate,
            step=state.step + 1,
        )
        return new_state, {"loss": loss}

    return jax.jit(step, donate_argnums=(0,) if donate else ())
