"""Stall watchdog: warn when the step cadence breaks.

MPMD-style distributed training lives or dies on straggler visibility
(arXiv:2412.14374): a hung collective, a wedged data loader or a
preempted host shows up as... nothing — the loop simply stops printing.
This watchdog turns "nothing" into a signal: the trainer calls
:meth:`StepWatchdog.beat` once per step, a daemon thread compares the
time since the last beat against ``factor ×`` the **rolling median**
step time (median, not mean: one slow checkpoint step must not inflate
the baseline), and a breach fires ``on_stall`` — by default a warning
plus ``fdtpu_watchdog_stalls_total`` in the registry, so a scraper can
alert on it remotely.  The warning names the innermost ACTIVE
span/phase at stall time (:func:`..obs.spans.innermost_active` — the
trainer's phase brackets register there even without a tracer), so an
episode says "stalled in 'dispatch'" instead of just "stalled";
:attr:`last_where` keeps it readable for ``on_stall`` callbacks.

A stall that persists ``escalate_after`` further threshold windows
fires ONE **escalation** (``fdtpu_watchdog_escalations_total`` + the
``on_escalate`` abort callback): the warn says "slow", the escalation
says "wedged for good" — the signal ``bin/supervise.py`` SIGKILLs and
elastically resumes on.

The existing OOM-skip counter folds in through :meth:`note_skip`: a
skipped batch both keeps the heartbeat alive (the loop IS making
progress) and increments ``fdtpu_train_oom_skipped_total`` — one place
to watch for "training is quietly throwing work away".  Its PROACTIVE
sibling is :meth:`note_headroom`: the loop reports the minimum HBM
headroom ratio (``obs.memstats.min_headroom_ratio``) and the watchdog
keeps the ``fdtpu_hbm_headroom_ratio`` gauge current and fires ONE
low-headroom warning per episode when it drops under ``headroom_warn``
— the OOM-margin alarm that rings BEFORE the allocator loses, next to
the counter that tallies the batches lost after.

The check logic lives in :meth:`poll` so tests drive it synchronously;
the thread is just ``poll`` on a timer.
"""

from __future__ import annotations

import contextlib
import statistics
import sys
import threading
import time
from collections import deque
from typing import Callable, Optional

from .metrics import Registry, get_registry

__all__ = ["StepWatchdog"]


class StepWatchdog:
    """Heartbeat monitor for a stepping loop.

    Parameters
    ----------
    factor: stall threshold as a multiple of the rolling-median step
        time (default 5× — cadence jitter from eval/checkpoint cycles
        stays under it, a wedged collective does not)
    min_interval: floor on the threshold in seconds (median decode steps
        can be sub-millisecond; waking ops for a 5 ms "stall" is noise)
    window: number of recent step intervals in the rolling median
    check_every: watchdog thread poll period in seconds
    warmup: beats to observe before arming (the first steps include
        compiles and are not cadence)
    on_stall: ``fn(elapsed_sec, threshold_sec)`` — defaults to a stderr
        warning; fired ONCE per stall episode (a beat re-arms it)
    escalate_after: a stall that persists this many FURTHER threshold
        windows (i.e. ``elapsed > (1 + escalate_after) × threshold``)
        counts an ESCALATION — ``fdtpu_watchdog_escalations_total``
        increments and ``on_escalate`` fires, once per stall (a beat
        re-arms).  This is the wedged-collective signal: a one-off warn
        says "slow", the escalation says "this loop is never coming
        back" — the counter a supervisor (``bin/supervise.py``)
        SIGKILLs on.  0 (default) preserves the warn-once behavior.
    on_escalate: ``fn(elapsed_sec, threshold_sec)`` abort callback run
        at escalation — e.g. dump state and ``os._exit``; default is a
        stderr warning (the counter alone is the remote signal)
    headroom_warn: minimum HBM headroom ratio below which
        :meth:`note_headroom` fires its once-per-episode warning (an
        episode ends when headroom recovers above the threshold);
        0 disables the alert while the gauge stays live
    registry: metrics registry (default: the process registry)
    """

    def __init__(
        self,
        factor: float = 5.0,
        min_interval: float = 1.0,
        window: int = 64,
        check_every: float = 0.5,
        warmup: int = 3,
        on_stall: Optional[Callable[[float, float], None]] = None,
        escalate_after: int = 0,
        on_escalate: Optional[Callable[[float, float], None]] = None,
        headroom_warn: float = 0.05,
        registry: Optional[Registry] = None,
        name_prefix: str = "fdtpu",
    ):
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1, got {factor}")
        if escalate_after < 0:
            raise ValueError(
                f"escalate_after must be >= 0, got {escalate_after}")
        if not 0.0 <= headroom_warn < 1.0:
            raise ValueError(
                f"headroom_warn must be in [0, 1), got {headroom_warn}")
        self.factor = factor
        self.min_interval = min_interval
        self.check_every = check_every
        self.warmup = warmup
        self.on_stall = on_stall
        self.escalate_after = escalate_after
        self.on_escalate = on_escalate
        self.registry = registry or get_registry()
        self._intervals: deque = deque(maxlen=window)
        self._lock = threading.Lock()
        self._last_beat: Optional[float] = None
        self._beats = 0
        self._fired = False  # one warning per stall episode
        self._escalated = False  # one escalation per stall episode
        self._paused = 0  # pause() nesting depth
        # the beat ending a pause-containing iteration measures only the
        # post-pause remainder — a bogus near-zero interval that would
        # collapse the median; skip recording it (once)
        self._skip_interval = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stalls = self.registry.counter(
            f"{name_prefix}_watchdog_stalls_total",
            "stall episodes (no step within factor x rolling-median step time)",
        )
        self._stalled = self.registry.gauge(
            f"{name_prefix}_watchdog_stalled",
            "1 while the loop is currently stalled, 0 otherwise",
        )
        self._skips = self.registry.counter(
            f"{name_prefix}_train_oom_skipped_total",
            "batches skipped by OOM fault tolerance",
        )
        self._escalations = self.registry.counter(
            f"{name_prefix}_watchdog_escalations_total",
            "stalls that persisted past escalate_after further threshold "
            "windows (the wedged-collective signal supervisors kill on)",
        )
        # the OOM-margin pair: the gauge is the live margin, the
        # counter tallies low-headroom EPISODES (warn-once semantics,
        # mirroring the stall counter)
        self.headroom_warn = headroom_warn
        self._headroom_low = False
        self._headroom = self.registry.gauge(
            f"{name_prefix}_hbm_headroom_ratio",
            "min over devices of (bytes_limit - bytes_in_use) / "
            "bytes_limit — the OOM margin; NaN when unavailable",
        )
        self._low_headroom_total = self.registry.counter(
            f"{name_prefix}_watchdog_low_headroom_total",
            "episodes where HBM headroom dropped below headroom_warn "
            "(the proactive sibling of the OOM-skip counter)",
        )
        self._stalled.set(0)
        # NaN until the loop reports a real margin: 0.0 would read as
        # "about to OOM" on backends that simply have no memory stats
        self._headroom.set(float("nan"))
        #: innermost active span/phase at the most recent stall fire
        #: (None when nothing was bracketed) — set BEFORE on_stall runs
        self.last_where: Optional[str] = None

    # -- loop side -----------------------------------------------------
    def beat(self) -> None:
        """One step completed (call from the training/serving loop)."""
        now = time.monotonic()
        with self._lock:
            if self._last_beat is not None and not self._skip_interval:
                self._intervals.append(now - self._last_beat)
            self._skip_interval = False
            self._last_beat = now
            self._beats += 1
            if self._fired:
                self._fired = False
                self._escalated = False
                self._stalled.set(0)

    def note_skip(self, n: int = 1) -> None:
        """An OOM-skipped batch: progress (heartbeat) + a counted loss
        of work (the reference's dead ``num_missed``, now scrapeable)."""
        self._skips.inc(n)
        self.beat()

    def note_headroom(self, ratio: Optional[float]) -> bool:
        """Report the current minimum HBM headroom ratio (the trainer
        samples ``obs.memstats.min_headroom_ratio()`` per step).  Keeps
        the ``fdtpu_hbm_headroom_ratio`` gauge current and fires ONE
        warning + counter tick per low-headroom EPISODE — an episode
        opens when the ratio drops under ``headroom_warn`` and closes
        when it recovers, so a run hovering at 3% margin pages once,
        not once per step.  ``None`` (no memory stats on this backend)
        is a no-op: the gauge stays NaN, never a fake alarm.  Returns
        True iff a new episode fired."""
        if ratio is None:
            return False
        ratio = float(ratio)
        self._headroom.set(ratio)
        if not self.headroom_warn:
            return False
        if ratio >= self.headroom_warn:
            self._headroom_low = False
            return False
        if self._headroom_low:
            return False
        self._headroom_low = True
        self._low_headroom_total.inc()
        print(
            f"obs.watchdog: LOW HBM HEADROOM — min device margin "
            f"{ratio:.1%} (< {self.headroom_warn:.1%}); the next "
            "allocation spike (longer batch, eval, checkpoint "
            "snapshot) may OOM — shrink the batch or re-plan the "
            "layout (bin/fit.py)",
            file=sys.stderr,
        )
        return True

    @contextlib.contextmanager
    def pause(self):
        """Suspend stall detection around KNOWN-long legitimate work
        (a checkpoint's synchronous device→host snapshot, a full eval
        pass).  Without this, any in-loop phase longer than the
        threshold reads as a stall and flips /healthz to 503 — paging
        an operator about a checkpoint is how watchdogs get disabled.
        The interval restarts on exit so the paused phase neither fires
        nor pollutes the rolling median.  Nests."""
        with self._lock:
            self._paused += 1
        try:
            yield
        finally:
            now = time.monotonic()
            with self._lock:
                self._paused -= 1
                # restart the measurement window; NEITHER the paused
                # phase's duration NOR the post-pause remainder of this
                # iteration may enter the cadence intervals (the first
                # would inflate the median, the second collapse it)
                self._last_beat = now
                self._skip_interval = True

    # -- watchdog side -------------------------------------------------
    def threshold(self) -> Optional[float]:
        """Current stall threshold in seconds (None while unarmed)."""
        with self._lock:
            if self._beats <= self.warmup or len(self._intervals) < 2:
                return None
            med = statistics.median(self._intervals)
        return max(self.factor * med, self.min_interval)

    def poll(self, now: Optional[float] = None) -> bool:
        """One check; returns True iff a NEW stall episode fired.
        (Public so tests — or a caller without threads — drive it
        synchronously.)  A stall that persists ``escalate_after``
        further threshold windows additionally fires ONE escalation —
        without it a permanent stall would warn once and then sit
        silent forever, indistinguishable from a slow phase."""
        thr = self.threshold()
        with self._lock:
            last = self._last_beat
            already = self._fired
            paused = self._paused > 0
        if thr is None or last is None or paused:
            return False
        elapsed = (now if now is not None else time.monotonic()) - last
        if already:
            self._maybe_escalate(elapsed, thr)
            return False
        if elapsed <= thr:
            return False
        with self._lock:
            if self._fired:  # lost the race with another poll
                return False
            self._fired = True
        from .spans import innermost_active

        self.last_where = innermost_active()
        where = (f" — stalled inside span/phase {self.last_where!r}"
                 if self.last_where else "")
        self._stalls.inc()
        self._stalled.set(1)
        if self.on_stall is not None:
            self.on_stall(elapsed, thr)
        else:
            print(
                f"obs.watchdog: STALL — no step for {elapsed:.1f}s "
                f"(threshold {thr:.1f}s = {self.factor} x median step); "
                "a collective, the data loader, or a checkpoint write "
                f"may be wedged{where}",
                file=sys.stderr,
            )
        return True

    def _maybe_escalate(self, elapsed: float, thr: float) -> None:
        if not self.escalate_after or elapsed <= thr * (
                1 + self.escalate_after):
            return
        with self._lock:
            if self._escalated or not self._fired:
                return
            self._escalated = True
        from .spans import innermost_active

        self.last_where = innermost_active()
        where = (f" inside span/phase {self.last_where!r}"
                 if self.last_where else "")
        self._escalations.inc()
        if self.on_escalate is not None:
            self.on_escalate(elapsed, thr)
        else:
            print(
                f"obs.watchdog: ESCALATION — the stall{where} has "
                f"persisted {elapsed:.1f}s (> {1 + self.escalate_after} x "
                f"the {thr:.1f}s threshold); this loop is likely wedged "
                "for good (hung collective / dead backend) — a "
                "supervisor should SIGKILL and resume elastically",
                file=sys.stderr,
            )

    def _run(self) -> None:
        while not self._stop.wait(self.check_every):
            try:
                self.poll()
            except Exception as e:  # noqa: BLE001 — a watchdog that can
                # crash is a watchdog that silently stops watching
                print(f"obs.watchdog: poll failed: {type(e).__name__}: {e}",
                      file=sys.stderr)

    def start(self) -> "StepWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="fdtpu-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(1.0, 4 * self.check_every))
            self._thread = None

    def __enter__(self) -> "StepWatchdog":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
