from .losses import logitcrossentropy, crossentropy, mse
from .metrics import topkaccuracy, onehot, showpreds
from .attention import attention_core, blockwise_attention, dot_product_attention

__all__ = [
    "logitcrossentropy",
    "crossentropy",
    "mse",
    "topkaccuracy",
    "onehot",
    "showpreds",
    "dot_product_attention",
    "blockwise_attention",
    "attention_core",
]
