"""Deadline discipline of the hardware-benchmark chain.

The axon runtime grants ONE TPU client at a time, and the driver runs
the official bench.py at round end — so no watcher attempt, session
stage, or sweep child may hold (or queue for) the grant past the
exported deadline.  These tests drive the chain's skip paths with an
already-passed deadline: everything must decline to launch, quickly,
without ever creating a TPU client.
"""

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_hw_session_skips_every_stage_past_deadline(tmp_path):
    env = {**os.environ, "HW_DEADLINE_EPOCH": str(int(time.time()))}
    t0 = time.monotonic()
    p = subprocess.run(
        ["sh", os.path.join(REPO, "benchmarks", "hw_session.sh"), str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=60,
    )
    assert p.returncode == 0, p.stderr[-500:]
    assert time.monotonic() - t0 < 30, "skip path must not launch anything slow"
    log = (tmp_path / "session.log").read_text()
    # all 23 stage launches declined (incl. the 4 flash-vs-blockwise LM
    # rows, the windowed/GQA rows, the 3 serving decode rows, the
    # flash-decode kernel row, the pipeline planner/zero-bubble row,
    # and the auto-layout picker row); the chain still runs to
    # completion
    assert log.count("skipping next stage") == 23, log
    assert "session complete" in log
    # nothing produced measurement output
    assert not (tmp_path / "bench.jsonl").exists()


def test_step_sweep_stops_before_deadline():
    env = {**os.environ, "SWEEP_DEADLINE_EPOCH": "1", "SWEEP_PLATFORM": "cpu"}
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "step_sweep.py")],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert p.returncode == 0, p.stderr[-500:]
    # structural check, robust to config-list edits: every per-config
    # line declined, nothing measured.  (Skipped configs never reach the
    # results list, so the {"sweep": []} summary contributes no rows.)
    per_config = [
        ln for ln in p.stdout.splitlines()
        if '"config"' in ln and '"sweep"' not in ln
    ]
    assert per_config, p.stdout[-800:]
    assert all('"skipped: deadline"' in ln for ln in per_config), p.stdout[-800:]
    assert '"img_per_sec_per_chip"' not in p.stdout


def test_hw_watch_declines_past_deadline(tmp_path):
    """With an expired deadline the watcher exits via the early
    no-attempt-fits gate — BEFORE the wait-for-in-flight loop, so a
    wedged orphan client cannot stall the exit.  OUT is pointed at a
    scratch dir so a live production watcher's flock on benchmarks/hw
    cannot shadow the path under test."""
    t0 = time.monotonic()
    p = subprocess.run(
        ["sh", os.path.join(REPO, "benchmarks", "hw_watch.sh"), str(tmp_path)],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "WATCH_DEADLINE_EPOCH": "1"},
    )
    assert p.returncode == 0, p.stderr[-500:]
    assert "no attempt fits before the deadline" in p.stdout, p.stdout
    assert time.monotonic() - t0 < 20


def test_hw_watch_honors_stop_file(tmp_path):
    (tmp_path / ".stop").touch()
    p = subprocess.run(
        ["sh", os.path.join(REPO, "benchmarks", "hw_watch.sh"), str(tmp_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert p.returncode == 0, p.stderr[-500:]
    assert "stop file present" in p.stdout, p.stdout
