"""FDT103 negative: pinned dtypes and non-literal arguments."""
import jax
import jax.numpy as jnp


@jax.jit
def scaled(x):
    return x * jnp.array(1.5, dtype=jnp.float32)


@jax.jit
def shifted(x):
    return x + jnp.array(-3, jnp.int32)  # positional dtype


@jax.jit
def from_arg(x):
    return jnp.asarray(x)  # not a scalar literal


def host_side():
    return jnp.array(1.5)  # not jit-reachable — eager, no retrace trap
