"""ctypes binding for the native C++ image-ingest library.

The reference's data path reaches native code through dependencies —
libjpeg-turbo via JpegTurbo.jl (src/imagenet.jl:32) and the
ImageMagick/Images.jl stack for resize/filter (src/preprocess.jl:39-41) —
with one Julia thread per image (src/imagenet.jl:44-46).  This framework
ships its own native pipeline (``native/fd_native.cpp``): libjpeg decode,
antialiased triangle-filter resize, center crop, normalize, batched over
an internal C++ thread pool.  ctypes releases the GIL for the whole batch
call, so ingest runs fully parallel to the training step dispatch.

The library is compiled on first use (g++, ~1s) and cached at
``native/build/libfdnative.so``.  Everything degrades gracefully: if the
toolchain or libjpeg is missing, callers fall back to the PIL path in
``preprocess.py`` (same output contract, looser perf).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Optional, Sequence

import numpy as np

from .preprocess import IMAGENET_MEAN, IMAGENET_STD

__all__ = ["available", "load_batch", "preprocess_rgb", "decode_jpeg_file", "lib_path"]

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_ROOT, "native", "fd_native.cpp")
_SO = os.path.join(_ROOT, "native", "build", "libfdnative.so")

_lock = threading.Lock()
_lib = None
_tried = False


def lib_path() -> str:
    return _SO


_ABI_VERSION = 3  # must match fd_version() in fd_native.cpp


def _build() -> bool:
    """Compile to a per-process temp file then os.replace() into place —
    atomic, so concurrent builders (multi-host shared filesystem,
    pytest-xdist) never dlopen a half-written library."""
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3", "-fPIC", "-std=c++17", "-shared",
        "-o", tmp, _SRC, "-ljpeg", "-lpthread",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
            os.path.exists(_SRC) and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        ):
            if not os.path.exists(_SRC) or not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.fd_version.restype = ctypes.c_int
        if lib.fd_version() != _ABI_VERSION:
            # stale prebuilt library from an older source — rebuild once
            if not _build():
                return None
            lib = ctypes.CDLL(_SO)
            lib.fd_version.restype = ctypes.c_int
            if lib.fd_version() != _ABI_VERSION:
                return None
        lib.fd_preprocess_rgb.restype = ctypes.c_int
        lib.fd_preprocess_rgb.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.fd_decode_jpeg_file.restype = ctypes.c_int
        lib.fd_decode_jpeg_file.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ]
        lib.fd_load_batch.restype = ctypes.c_int
        lib.fd_load_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
            ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int, ctypes.POINTER(ctypes.c_float),
            ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
        ]
        lib.fd_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    """True if the native library is present (building it if needed)."""
    return _load() is not None


def _fp(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _norm_params(mean, std):
    m = np.ascontiguousarray(mean, np.float32)
    s = np.ascontiguousarray(std, np.float32)
    return m, s


def _aug_ptr(augment, expected_shape):
    """None → NULL; else a C-contiguous float32 array of aug params and
    its pointer (the array must stay referenced for the call's lifetime)."""
    if augment is None:
        return None, None
    arr = np.ascontiguousarray(augment, np.float32)
    if arr.shape != expected_shape:
        raise ValueError(f"augment params must have shape {expected_shape}, got {arr.shape}")
    return arr, _fp(arr)


def preprocess_rgb(
    rgb: np.ndarray,
    crop: int = 224,
    resize: int = 256,
    mean: Sequence[float] = IMAGENET_MEAN,
    std: Sequence[float] = IMAGENET_STD,
    compat_double_normalize: bool = False,
    augment=None,
) -> np.ndarray:
    """Native resize→crop→normalize for one HWC uint8 RGB array.

    ``augment``: optional 5-vector ``(area, ratio, u, v, flip)`` from
    ``preprocess.sample_augment_params`` switching the geometric stage to
    RandomResizedCrop+hflip (train path); None is the eval path.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    rgb = np.ascontiguousarray(rgb, np.uint8)
    h, w = rgb.shape[:2]
    out = np.empty((crop, crop, 3), np.float32)
    m, s = _norm_params(mean, std)
    aug_arr, aug_p = _aug_ptr(augment, (5,))
    rc = lib.fd_preprocess_rgb(
        rgb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), h, w,
        resize, crop, _fp(m), _fp(s),
        1 if compat_double_normalize else 0, _fp(out), aug_p,
    )
    del aug_arr
    if rc != 0:
        raise ValueError(f"fd_preprocess_rgb failed (rc={rc})")
    return out


def decode_jpeg_file(path: str) -> np.ndarray:
    """Native libjpeg decode of one file → HWC uint8 RGB."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    buf = ctypes.POINTER(ctypes.c_uint8)()
    h = ctypes.c_int()
    w = ctypes.c_int()
    rc = lib.fd_decode_jpeg_file(path.encode(), ctypes.byref(buf),
                                 ctypes.byref(h), ctypes.byref(w))
    if rc != 0:
        raise ValueError(f"cannot decode {path} (rc={rc})")
    try:
        n = h.value * w.value * 3
        arr = np.ctypeslib.as_array(buf, shape=(n,)).copy()
    finally:
        lib.fd_free(buf)
    return arr.reshape(h.value, w.value, 3)


def load_batch(
    paths: Sequence[str],
    crop: int = 224,
    resize: int = 256,
    mean: Sequence[float] = IMAGENET_MEAN,
    std: Sequence[float] = IMAGENET_STD,
    compat_double_normalize: bool = False,
    num_threads: int = 8,
    out: Optional[np.ndarray] = None,
    strict: bool = True,
    fallback: Optional[Callable[..., np.ndarray]] = None,
    augs: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Full native pipeline for a list of JPEG files → (N, crop, crop, 3).

    The ``minibatch`` builder analog (src/imagenet.jl:37-48): decode +
    preprocess every file on a C++ thread pool into a preallocated
    float32 batch.  Slots the native decoder cannot handle (e.g. PNG
    bytes hiding behind a ``.JPEG`` extension) are retried through
    ``fallback(path) -> HWC float32`` when given — so a handful of odd
    files degrade to the slow path instead of poisoning the batch.  With
    ``strict`` (default) anything still failing after the fallback
    raises; otherwise those slots stay zero-filled.

    ``augs``: optional ``(N, 5)`` float32 of per-image
    ``sample_augment_params`` rows enabling RandomResizedCrop+hflip
    (train path).  When given, the fallback is called as
    ``fallback(path, aug_row)`` so slow-path slots see the same
    augmentation.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    if not 1 <= crop <= resize:
        raise ValueError(f"need 1 <= crop <= resize, got crop={crop} resize={resize}")
    n = len(paths)
    if out is None:
        out = np.empty((n, crop, crop, 3), np.float32)
    if out.shape != (n, crop, crop, 3) or out.dtype != np.float32:
        raise ValueError(
            f"out must be float32 {(n, crop, crop, 3)}, got {out.dtype} {out.shape}"
        )
    if not out.flags.c_contiguous:
        raise ValueError("out must be C-contiguous (native code writes raw memory)")
    enc = [p.encode() for p in paths]
    arr = (ctypes.c_char_p * n)(*enc)
    m, s = _norm_params(mean, std)
    errbuf = ctypes.create_string_buffer(512)
    failed = np.zeros(n, np.uint8)
    aug_arr, aug_p = _aug_ptr(augs, (n, 5))
    failures = lib.fd_load_batch(
        arr, n, resize, crop, _fp(m), _fp(s),
        1 if compat_double_normalize else 0, _fp(out),
        num_threads, errbuf, len(errbuf),
        failed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), aug_p,
    )
    if failures:
        still_failed = []
        first_fb_err = None
        for i in np.nonzero(failed)[0]:
            if fallback is not None:
                try:
                    if aug_arr is None:
                        out[i] = fallback(paths[i])
                    else:
                        out[i] = fallback(paths[i], aug_arr[i])
                    continue
                except Exception as e:  # noqa: BLE001 — any decode error → slot failed
                    first_fb_err = first_fb_err or e
            still_failed.append(int(i))
        if still_failed and strict:
            detail = errbuf.value.decode(errors="replace")
            if first_fb_err is not None:
                detail += f"; fallback: {first_fb_err}"
            raise ValueError(
                f"{len(still_failed)}/{n} images failed to load (first: {detail})"
            )
    return out
