"""Pallas TPU flash-attention kernel.

Net-new TPU scope (the reference has no attention and no custom kernels;
its native compute all comes from CUDNN via dependencies — SURVEY §2
"native dependencies").  This is the framework's hand-written hot-op:
fused flash attention that keeps the [block_q, block_k] score tile in
VMEM, accumulates the online softmax in f32 scratch, and never
materializes the [Tq, Tk] score matrix in HBM.

Design (standard TPU flash schedule):

* grid = (batch*heads, Tq/block_q, Tk/block_k), KV innermost — the TPU
  grid is sequential per core, so VMEM scratch (acc, m, l) carries the
  online-softmax state across the KV dimension;
* Q/K/V blocks are DMA'd HBM→VMEM by ``pallas_call`` per the BlockSpecs;
  the two matmuls (q·kᵀ and p·v) hit the MXU with f32 accumulation;
* causal masking uses global positions; fully-masked KV blocks are
  skipped with ``pl.when`` (no MXU work);
* backward: ``jax.custom_vjp`` recomputes via the XLA blockwise kernel
  (memory-bounded; a dedicated Pallas backward is future work).

On non-TPU backends the same kernel runs in interpreter mode, so tests
exercise identical code on the CPU CI mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import NEG_INF, blockwise_attention, online_softmax_update

__all__ = ["flash_attention"]

# m/l scratch rows are replicated across the VPU lane width.
_LANES = 128


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale, causal, tk_valid, causal_offset, padded,
):
    """``causal_offset = Tk_valid - Tq_valid`` end-aligns the causal mask
    (query i attends keys <= i + offset), matching
    ``dot_product_attention``'s KV-cache-decode convention."""
    _, block_q, _ = q_ref.shape
    _, block_k, _ = k_ref.shape
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = kj * block_k

    def _body():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]

        if causal or padded:
            k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = k_pos < tk_valid
            if causal:
                q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
                mask &= k_pos <= q_pos + causal_offset
        else:
            mask = None  # aligned non-causal: skip mask VPU work entirely

        p, corr, m_new, l_new = online_softmax_update(
            s, m_ref[:, 0], l_ref[:, 0], mask=mask
        )
        acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    if causal:
        # Skip KV blocks entirely above the causal diagonal (no MXU work).
        pl.when(k_start <= q_start + block_q - 1 + causal_offset)(_body)
    else:
        _body()

    @pl.when(kj == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _pad_seq(x, block):
    pad = -x.shape[1] % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / (d**0.5)
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)

    # Fold heads into batch: kernel operates on [BH, T, D].
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)
    qf = _pad_seq(fold(q), block_q)
    kf = _pad_seq(fold(k), block_k)
    vf = _pad_seq(fold(v), block_k)
    tq_p, tk_p = qf.shape[1], kf.shape[1]

    grid = (b * h, tq_p // block_q, tk_p // block_k)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, tk_valid=tk,
        causal_offset=tk - tq, padded=tk_p != tk,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tq_p, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out[:, :tq].reshape(b, h, tq, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Fused flash attention, [B, T, H, D] → [B, T, H, D].

    Runs the Pallas TPU kernel on TPU and the same kernel under the
    Pallas interpreter elsewhere (so CPU tests cover the real kernel).
    Numerics match ``dot_product_attention`` to f32 accumulation.
    """
    interpret = jax.default_backend() != "tpu"
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)


def _fwd(q, k, v, causal, block_q, block_k):
    return flash_attention(q, k, v, causal, block_q, block_k), (q, k, v)


def _bwd(causal, block_q, block_k, res, g):
    q, k, v = res
    # Memory-bounded recompute backward via the XLA blockwise kernel
    # (identical online-softmax numerics to the forward).
    _, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(
            q, k, v, block_size=block_k, causal=causal
        ),
        q, k, v,
    )
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
