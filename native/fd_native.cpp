// fd_native — native image-ingest pipeline for fluxdistributed_tpu.
//
// TPU-native re-implementation of the reference's host-side data path
// (src/imagenet.jl:28-48 fproc + src/preprocess.jl:30-67), which leans on
// libjpeg-turbo (JpegTurbo.jl) and ImageMagick/Images.jl native code and
// runs one Julia thread per image.  Here the whole hot path — file read,
// JPEG decode (libjpeg), antialiased resize, center crop, normalize —
// is C++ behind a C ABI, with an internal std::thread pool per batch.
// Python binds via ctypes (no pybind11 in the image); the GIL is
// released for the whole batch call.
//
// API (all functions return 0 on success unless noted):
//   fd_version()                     -> int version
//   fd_preprocess_rgb(...)           -> resize+crop+normalize one RGB image
//   fd_load_batch(paths, n, ...)     -> full pipeline for n files, threaded
//   fd_decode_jpeg_file(path, ...)   -> decode only (caller frees via fd_free)
//
// Layout: outputs are float32 HWC (NHWC once batched) — the TPU-native
// layout (the reference's WHCN permute is a Julia memory-order artifact).

#include <cstddef>
#include <cstdio>

#include <jpeglib.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {

int fd_version() { return 3; }

void fd_free(void* p) { std::free(p); }

}  // extern "C"

// ---------------------------------------------------------------------------
// JPEG decode (libjpeg with longjmp error handler — the library's default
// error handler exit()s the process, unacceptable in a training job).
// ---------------------------------------------------------------------------

namespace {

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
  char msg[JMSG_LENGTH_MAX];
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* e = reinterpret_cast<JpegErr*>(cinfo->err);
  (*cinfo->err->format_message)(cinfo, e->msg);
  longjmp(e->jb, 1);
}

// Decode JPEG bytes to RGB8. Returns malloc'd buffer (h*w*3) or nullptr.
//
// Locals touched after setjmp are raw pointers declared `volatile` (a
// non-volatile local modified between setjmp and longjmp is indeterminate
// after the jump), and cleanup uses free() only — no destructors are
// skipped by the longjmp.  CMYK/YCCK (Adobe) sources are decoded as
// JCS_CMYK and converted here — libjpeg cannot emit RGB for them, and
// ImageNet is known to contain a handful of such files.
uint8_t* decode_jpeg(const uint8_t* buf, size_t len, int* h, int* w,
                     std::string* err) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  uint8_t* volatile out = nullptr;
  uint8_t* volatile rowbuf = nullptr;
  if (setjmp(jerr.jb)) {
    if (err) *err = jerr.msg;
    jpeg_destroy_decompress(&cinfo);
    std::free(out);
    std::free(rowbuf);
    return nullptr;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf), len);
  jpeg_read_header(&cinfo, TRUE);
  const bool cmyk = cinfo.jpeg_color_space == JCS_CMYK ||
                    cinfo.jpeg_color_space == JCS_YCCK;
  cinfo.out_color_space = cmyk ? JCS_CMYK : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int W = cinfo.output_width, H = cinfo.output_height;
  const int C = cinfo.output_components;  // 3 (RGB) or 4 (CMYK)
  out = static_cast<uint8_t*>(std::malloc(size_t(W) * H * 3));
  rowbuf = static_cast<uint8_t*>(std::malloc(size_t(W) * C));
  if (!out || !rowbuf) {
    if (err) *err = "malloc failed";
    jpeg_destroy_decompress(&cinfo);
    std::free(out);
    std::free(rowbuf);
    return nullptr;
  }
  JSAMPROW rp = rowbuf;
  while (cinfo.output_scanline < cinfo.output_height) {
    int y = cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &rp, 1);
    uint8_t* dst = out + size_t(y) * W * 3;
    if (cmyk) {
      // Adobe stores inverted CMYK: RGB = (C,M,Y) scaled by K.
      for (int x = 0; x < W; ++x) {
        const uint8_t* p = rowbuf + size_t(x) * 4;
        const int k = p[3];
        dst[3 * x] = uint8_t(p[0] * k / 255);
        dst[3 * x + 1] = uint8_t(p[1] * k / 255);
        dst[3 * x + 2] = uint8_t(p[2] * k / 255);
      }
    } else if (C == 3) {
      std::memcpy(dst, rowbuf, size_t(W) * 3);
    } else {  // defensive: expand single channel
      for (int x = 0; x < W; ++x)
        dst[3 * x] = dst[3 * x + 1] = dst[3 * x + 2] = rowbuf[size_t(x) * C];
    }
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  std::free(rowbuf);
  *h = H;
  *w = W;
  return out;
}

// ---------------------------------------------------------------------------
// Antialiased separable resize (triangle/linear filter, support scaled by
// the reduction factor — the same family Pillow's BILINEAR uses, and the
// functional equivalent of the reference's Gaussian-lowpass-then-imresize,
// src/preprocess.jl:30-42).  float32 intermediates.
// ---------------------------------------------------------------------------

struct FilterTaps {
  std::vector<int> first;     // first source index per output pixel
  std::vector<int> count;     // tap count per output pixel
  std::vector<float> weight;  // taps, row-major [out, maxcount]
  int maxcount = 0;
};

FilterTaps build_taps(int in_size, int out_size) {
  FilterTaps t;
  const double scale = double(in_size) / out_size;
  const double support = std::max(1.0, scale);  // widen when downscaling
  t.maxcount = int(std::ceil(support)) * 2 + 1;
  t.first.resize(out_size);
  t.count.resize(out_size);
  t.weight.assign(size_t(out_size) * t.maxcount, 0.f);
  for (int o = 0; o < out_size; ++o) {
    const double center = (o + 0.5) * scale;
    int lo = std::max(0, int(std::floor(center - support)));
    int hi = std::min(in_size, int(std::ceil(center + support)));
    double sum = 0;
    int cnt = hi - lo;
    for (int i = 0; i < cnt; ++i) {
      double x = (lo + i + 0.5 - center) / support;  // triangle filter
      double wv = std::max(0.0, 1.0 - std::fabs(x));
      t.weight[size_t(o) * t.maxcount + i] = float(wv);
      sum += wv;
    }
    if (sum > 0)
      for (int i = 0; i < cnt; ++i)
        t.weight[size_t(o) * t.maxcount + i] /= float(sum);
    t.first[o] = lo;
    t.count[o] = cnt;
  }
  return t;
}

// uint8 HWC RGB → float32 HWC resized (nh, nw).
void resize_rgb(const uint8_t* src, int h, int w, float* dst, int nh, int nw) {
  FilterTaps tx = build_taps(w, nw), ty = build_taps(h, nh);
  // horizontal pass: (h, w, 3) u8 → (h, nw, 3) f32
  std::vector<float> tmp(size_t(h) * nw * 3);
  for (int y = 0; y < h; ++y) {
    const uint8_t* row = src + size_t(y) * w * 3;
    float* orow = tmp.data() + size_t(y) * nw * 3;
    for (int o = 0; o < nw; ++o) {
      const float* wt = &tx.weight[size_t(o) * tx.maxcount];
      const int f = tx.first[o], c = tx.count[o];
      float r = 0, g = 0, b = 0;
      for (int i = 0; i < c; ++i) {
        const uint8_t* p = row + size_t(f + i) * 3;
        r += wt[i] * p[0];
        g += wt[i] * p[1];
        b += wt[i] * p[2];
      }
      orow[3 * o] = r;
      orow[3 * o + 1] = g;
      orow[3 * o + 2] = b;
    }
  }
  // vertical pass: (h, nw, 3) → (nh, nw, 3)
  for (int o = 0; o < nh; ++o) {
    const float* wt = &ty.weight[size_t(o) * ty.maxcount];
    const int f = ty.first[o], c = ty.count[o];
    float* orow = dst + size_t(o) * nw * 3;
    std::memset(orow, 0, size_t(nw) * 3 * sizeof(float));
    for (int i = 0; i < c; ++i) {
      const float* irow = tmp.data() + size_t(f + i) * nw * 3;
      const float wv = wt[i];
      for (int x = 0; x < nw * 3; ++x) orow[x] += wv * irow[x];
    }
  }
}

// Pixel crop rect from relative RandomResizedCrop params.  Shared
// contract with the Python implementation (_aug_rect in preprocess.py)
// — keep the two in sync.
void aug_rect(int h, int w, float area, float ratio, float u, float v,
              int* y0, int* x0, int* ch, int* cw) {
  const double target = double(area) * h * w;
  int tw = int(std::lround(std::sqrt(target * ratio)));
  int th = int(std::lround(std::sqrt(target / ratio)));
  if (tw < 1 || th < 1 || tw > w || th > h) {
    const int side = std::min(h, w);
    *y0 = (h - side) / 2;
    *x0 = (w - side) / 2;
    *ch = side;
    *cw = side;
    return;
  }
  *y0 = int(std::lround(double(v) * (h - th)));
  *x0 = int(std::lround(double(u) * (w - tw)));
  *ch = th;
  *cw = tw;
}

// resize smallest side → `resize`, center-crop `crop`, normalize.
// out: crop*crop*3 float32.  compat = reference double-normalize quirk.
// aug: optional 5 floats {area, ratio, u, v, flip} switching the
// geometric stage to RandomResizedCrop+hflip (train augmentation).
void preprocess_rgb(const uint8_t* rgb, int h, int w, int resize, int crop,
                    const float* mean, const float* stdv, int compat,
                    float* out, const float* aug) {
  std::vector<float> resized;
  int nw, top, left;
  bool flip = false;
  if (aug && aug[0] > 0.f) {
    int y0, x0, ch0, cw0;
    aug_rect(h, w, aug[0], aug[1], aug[2], aug[3], &y0, &x0, &ch0, &cw0);
    flip = aug[4] >= 0.5f;
    // crop the rect, then resize the region directly to crop×crop
    std::vector<uint8_t> region(size_t(ch0) * cw0 * 3);
    for (int y = 0; y < ch0; ++y)
      std::memcpy(region.data() + size_t(y) * cw0 * 3,
                  rgb + (size_t(y0 + y) * w + x0) * 3, size_t(cw0) * 3);
    resized.resize(size_t(crop) * crop * 3);
    if (ch0 == crop && cw0 == crop) {
      for (size_t i = 0; i < resized.size(); ++i) resized[i] = float(region[i]);
    } else {
      resize_rgb(region.data(), ch0, cw0, resized.data(), crop, crop);
    }
    nw = crop;
    top = 0;
    left = 0;
  } else {
    const double scale = double(resize) / std::min(h, w);
    int nh = std::max(resize, int(std::lround(h * scale)));
    nw = std::max(resize, int(std::lround(w * scale)));
    resized.resize(size_t(nh) * nw * 3);
    if (nh == h && nw == w) {
      for (size_t i = 0; i < resized.size(); ++i) resized[i] = float(rgb[i]);
    } else {
      resize_rgb(rgb, h, w, resized.data(), nh, nw);
    }
    top = (nh - crop) / 2;
    left = (nw - crop) / 2;
  }
  const float inv255 = 1.f / 255.f;
  for (int y = 0; y < crop; ++y) {
    const float* srow = resized.data() + (size_t(top + y) * nw + left) * 3;
    float* drow = out + size_t(y) * crop * 3;
    for (int x = 0; x < crop; ++x) {
      const int sx = flip ? (crop - 1 - x) : x;
      for (int ch = 0; ch < 3; ++ch) {
        float v = srow[3 * sx + ch] * inv255;
        drow[3 * x + ch] = (v - mean[ch]) / stdv[ch];
      }
    }
  }
  if (compat) {
    // Reference quirk (src/preprocess.jl:66 + src/imagenet.jl:34):
    // *255 then per-image standardization.
    const size_t n = size_t(crop) * crop * 3;
    double s = 0;
    for (size_t i = 0; i < n; ++i) {
      out[i] *= 255.f;
      s += out[i];
    }
    const double m = s / n;
    double var = 0;
    for (size_t i = 0; i < n; ++i) {
      const double d = out[i] - m;
      var += d * d;
    }
    // match numpy std (population) + the Python path's 1e-5 epsilon
    const float sd = float(std::sqrt(var / n)) + 1e-5f;
    for (size_t i = 0; i < n; ++i) out[i] = (out[i] - float(m)) / sd;
  }
}

bool read_file(const char* path, std::vector<uint8_t>* buf) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (n < 0) {
    std::fclose(f);
    return false;
  }
  buf->resize(size_t(n));
  size_t rd = n ? std::fread(buf->data(), 1, size_t(n), f) : 0;
  std::fclose(f);
  return rd == size_t(n);
}

}  // namespace

extern "C" {

// Decode + preprocess one in-memory RGB image.  aug: NULL or 5 floats
// {area, ratio, u, v, flip} enabling RandomResizedCrop+hflip.
int fd_preprocess_rgb(const uint8_t* rgb, int h, int w, int resize, int crop,
                      const float* mean, const float* stdv, int compat,
                      float* out, const float* aug) {
  if (!rgb || !out || h < 1 || w < 1 || crop > resize) return 1;
  preprocess_rgb(rgb, h, w, resize, crop, mean, stdv, compat, out, aug);
  return 0;
}

// Decode a JPEG file; *out is malloc'd (free with fd_free).
int fd_decode_jpeg_file(const char* path, uint8_t** out, int* h, int* w) {
  std::vector<uint8_t> buf;
  if (!read_file(path, &buf)) return 1;
  std::string err;
  uint8_t* rgb = decode_jpeg(buf.data(), buf.size(), h, w, &err);
  if (!rgb) return 2;
  *out = rgb;
  return 0;
}

// Full batch pipeline: n files → out (n, crop, crop, 3) float32.
// Threaded with an atomic work queue.  Returns the number of failed
// images (their slots are zero-filled and flagged in `failed` when
// non-null, so the caller can re-load them through a fallback decoder);
// errbuf holds the first error.  augs: NULL (eval path) or n×5 floats
// of per-image RandomResizedCrop+flip parameters.
int fd_load_batch(const char** paths, int n, int resize, int crop,
                  const float* mean, const float* stdv, int compat,
                  float* out, int nthreads, char* errbuf, int errlen,
                  unsigned char* failed, const float* augs) {
  if (n <= 0) return 0;
  if (!out || crop < 1 || resize < 1 || crop > resize) {
    if (errbuf && errlen > 0)
      std::snprintf(errbuf, size_t(errlen),
                    "invalid crop/resize (%d/%d): need 1 <= crop <= resize",
                    crop, resize);
    if (failed) std::memset(failed, 1, size_t(n));
    return n;
  }
  nthreads = std::max(1, std::min(nthreads, n));
  std::atomic<int> next(0), failures(0);
  std::atomic<bool> have_err(false);
  const size_t stride = size_t(crop) * crop * 3;
  auto worker = [&]() {
    for (;;) {
      const int i = next.fetch_add(1);
      if (i >= n) return;
      float* dst = out + size_t(i) * stride;
      std::vector<uint8_t> buf;
      std::string err;
      uint8_t* rgb = nullptr;
      int h = 0, w = 0;
      if (!read_file(paths[i], &buf)) {
        err = std::string("cannot read ") + paths[i];
      } else {
        rgb = decode_jpeg(buf.data(), buf.size(), &h, &w, &err);
      }
      if (!rgb) {
        std::memset(dst, 0, stride * sizeof(float));
        if (failed) failed[i] = 1;
        failures.fetch_add(1);
        if (!have_err.exchange(true) && errbuf && errlen > 0) {
          std::snprintf(errbuf, size_t(errlen), "%s: %s", paths[i],
                        err.c_str());
        }
        continue;
      }
      if (failed) failed[i] = 0;
      preprocess_rgb(rgb, h, w, resize, crop, mean, stdv, compat, dst,
                     augs ? augs + size_t(i) * 5 : nullptr);
      std::free(rgb);
    }
  };
  std::vector<std::thread> pool;
  for (int t = 1; t < nthreads; ++t) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
  return failures.load();
}

}  // extern "C"
