"""Synthetic datasets for tests, smoke runs and benchmarks.

The reference's tests use ``rand(Float32, ...)`` inputs and random one-hot
labels (test/single_device.jl:117-118) rather than stored fixtures; this
module is the structured version of that idea.  ``SyntheticDataset`` is
*learnable* (each class has a distinct mean image), so end-to-end trainer
tests can assert that the loss actually falls — a stronger check than the
reference's.
"""

from __future__ import annotations

import numpy as np

__all__ = ["SyntheticDataset"]


class SyntheticDataset:
    """Deterministic, learnable fake image classification data.

    Implements the framework's dataset protocol:

    * ``nsamples`` — table size (the analog of the reference's sample-key
      DataFrame row count, src/imagenet.jl:58-75),
    * ``nclasses``,
    * ``batch(rng, n, indices=None) -> (images [n,H,W,C] f32, labels [n] i32)``
      — with-replacement random sampling, as the reference's ``minibatch``
      sampler does (``key[rand(1:nrow, nsamples), :]`` src/imagenet.jl:24).
    """

    def __init__(
        self,
        nsamples: int = 1024,
        nclasses: int = 10,
        shape: tuple[int, int, int] = (32, 32, 3),
        seed: int = 0,
        noise: float = 0.3,
    ):
        self.nsamples = nsamples
        self.nclasses = nclasses
        self.shape = shape
        self.noise = noise
        root = np.random.default_rng(seed)
        # one low-frequency template per class
        self.templates = root.normal(0.0, 1.0, size=(nclasses, *shape)).astype(np.float32)
        self.labels_table = root.integers(0, nclasses, size=nsamples).astype(np.int32)

    def __len__(self) -> int:
        return self.nsamples

    def batch(self, rng: np.random.Generator, n: int, indices=None):
        if indices is None:
            indices = rng.integers(0, self.nsamples, size=n)  # with replacement
        labels = self.labels_table[np.asarray(indices)]
        imgs = self.templates[labels] + rng.normal(
            0.0, self.noise, size=(len(labels), *self.shape)
        ).astype(np.float32)
        return imgs.astype(np.float32), labels


class SyntheticTextDataset:
    """Deterministic, learnable fake token sequences for LM training.

    Sequences are drawn from a fixed low-entropy order-1 Markov chain:
    from each token, one successor has probability ``peak`` and the rest
    share the remainder.  An LM that learns the transition table drives
    next-token loss from ln(vocab) down toward the chain's conditional
    entropy — so "loss falls well below uniform" is a real learning
    signal, not memorization of a fixed batch.

    Protocol: ``batch(rng, n) -> tokens [n, seqlen] int32`` (with-
    replacement sampling semantics like :class:`SyntheticDataset` — each
    draw generates fresh sequences from the chain).
    """

    def __init__(
        self,
        vocab: int = 64,
        seqlen: int = 64,
        seed: int = 0,
        peak: float = 0.9,
    ):
        self.vocab = vocab
        self.seqlen = seqlen
        root = np.random.default_rng(seed)
        succ = root.permutation(vocab)  # the high-probability successor map
        probs = np.full((vocab, vocab), (1.0 - peak) / (vocab - 1), np.float64)
        probs[np.arange(vocab), succ] = peak
        self.transition = probs / probs.sum(axis=1, keepdims=True)
        self.cum = np.cumsum(self.transition, axis=1)

    def batch(self, rng: np.random.Generator, n: int):
        toks = np.empty((n, self.seqlen), np.int32)
        toks[:, 0] = rng.integers(0, self.vocab, size=n)
        u = rng.random((n, self.seqlen - 1))
        for t in range(1, self.seqlen):
            # inverse-CDF draw from each row's current-token distribution;
            # clip guards the fp edge where u >= cum[-1] (~1 - 1e-16)
            # would index one past the last token
            toks[:, t] = np.minimum(
                (self.cum[toks[:, t - 1]] < u[:, t - 1 : t]).sum(axis=1),
                self.vocab - 1,
            )
        return toks
