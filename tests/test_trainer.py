"""End-to-end trainer tests on the 8-device fake mesh — the analog of the
reference's integration test (test/single_device.jl:115-168) but stronger:
it asserts the loss actually falls and exercises the full
prepare_training → train → host-return pipeline including eval cadence,
checkpointing and the prefetch loader."""

import io
import os

import jax
import numpy as np
import pytest

from fluxdistributed_tpu import optim
from fluxdistributed_tpu.data import PrefetchLoader, SyntheticDataset
from fluxdistributed_tpu.models import MLP, SimpleCNN
from fluxdistributed_tpu.train import (
    ConsoleLogger,
    latest_step,
    load_checkpoint,
    prepare_training,
    save_checkpoint,
    train,
    with_logger,
)
from fluxdistributed_tpu.train.logging import NullLogger


@pytest.fixture(scope="module")
def mesh():
    from fluxdistributed_tpu import mesh as mesh_lib

    return mesh_lib.data_mesh(8)


def test_prefetch_loader_shapes_and_count(mesh):
    ds = SyntheticDataset(nsamples=256, nclasses=10, shape=(8, 8, 3))
    dl = PrefetchLoader(ds, mesh, batch_size=32, epochs=2, buffersize=3)
    assert len(dl) == 256 * 2 // 32
    batches = list(dl)
    assert len(batches) == len(dl)
    b = batches[0]
    assert b["image"].shape == (32, 8, 8, 3)
    assert b["label"].shape == (32, 10)
    # sharded across the mesh, one shard per device
    assert len(b["image"].sharding.device_set) == 8


def test_loader_surfaces_worker_errors(mesh):
    """A failing batch assembly must raise in the consumer, not deadlock
    the training loop (regression: worker death used to strand q.get())."""

    class ExplodingDataset:
        nclasses = 10

        def __len__(self):
            return 64

        def __init__(self):
            self.calls = 0

        def batch(self, rng, n):
            self.calls += 1
            if self.calls >= 2:
                raise OSError("disk went away")
            return np.zeros((n, 4, 4, 3), np.float32), np.zeros(n, np.int32)

    dl = PrefetchLoader(ExplodingDataset(), mesh, batch_size=8, cycles=5, num_threads=1)
    with pytest.raises(RuntimeError, match="prefetch worker failed"):
        list(dl)


def test_loader_rejects_indivisible_batch(mesh):
    ds = SyntheticDataset(nsamples=64)
    with pytest.raises(ValueError, match="divisible"):
        PrefetchLoader(ds, mesh, batch_size=30)


def test_end_to_end_training_loss_falls(mesh, tmp_path):
    ds = SyntheticDataset(nsamples=512, nclasses=10, shape=(8, 8, 3), seed=3)
    task = prepare_training(
        SimpleCNN(num_classes=10, features=8),
        ds,
        optim.momentum(0.05, 0.9),
        mesh=mesh,
        batch_size=64,
        cycles=60,
        val_dataset=ds,
        val_samples=64,
        seed=1,
    )
    out = io.StringIO()
    logger = ConsoleLogger(stream=out)
    first = float(task.eval_fn(task.state, task.val_batch)[0])
    params, mstate, task = train(
        task,
        print_every=10,
        eval_every=20,
        logger=logger,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=25,
    )
    last = float(task.eval_fn(task.state, task.val_batch)[0])
    assert last < first * 0.7, (first, last)
    assert int(task.state.step) == 60
    # host return is numpy
    assert isinstance(next(iter(jax.tree.leaves(params))), np.ndarray)
    text = out.getvalue()
    assert "cycle 0" in text and "cycle 10" in text   # print cadence
    assert "val_loss" in text and "val_top1" in text and "train_top5" in text
    # checkpoint written and resumable
    step = latest_step(str(tmp_path / "ckpt"))
    assert step is not None and step > 0
    restored = load_checkpoint(str(tmp_path / "ckpt"), task.state, mesh=mesh)
    assert int(restored.step) == step


def test_with_logger_context(mesh):
    ds = SyntheticDataset(nsamples=64, shape=(4, 4, 3))
    task = prepare_training(
        MLP(features=(16, 10)), ds, optim.descent(0.1), mesh=mesh, batch_size=16, cycles=2
    )
    with with_logger(NullLogger()):
        train(task, print_every=0, eval_every=0)
    assert int(task.state.step) == 2


def test_batchnorm_model_trains_and_stats_update(mesh):
    """The reference could not keep BatchNorm replicas in sync
    (test/single_device.jl:51-58 wraps everything in testmode!).  Here the
    sharded global-batch BN must (a) train and (b) keep identical stats on
    every device."""
    import flax.linen as nn
    import jax.numpy as jnp

    class BNNet(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = True):
            x = nn.Conv(8, (3, 3))(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            x = nn.relu(x)
            x = jnp.mean(x, axis=(1, 2))
            return nn.Dense(10, dtype=jnp.float32)(x)

    ds = SyntheticDataset(nsamples=128, shape=(8, 8, 3))
    task = prepare_training(
        BNNet(), ds, optim.momentum(0.05, 0.9), mesh=mesh, batch_size=32, cycles=5
    )
    zero_stats = jax.tree.leaves(jax.device_get(task.state.model_state))[0].copy()
    train(task, print_every=0, eval_every=0, logger=NullLogger())
    stats = task.state.model_state["batch_stats"]
    moved = any(
        not np.allclose(np.asarray(a), 0.0)
        for a in jax.tree.leaves(jax.device_get(stats))
    )
    assert moved, "running stats never updated"
    for leaf in jax.tree.leaves(stats):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)


def test_oom_detection_helper():
    from fluxdistributed_tpu.train.trainer import _is_oom

    assert _is_oom(RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating"))
    assert not _is_oom(RuntimeError("invalid argument"))


def test_val_batch_sampled_without_augmentation(mesh):
    """A val dataset carved from an augmenting train table must be
    evaluated through the eval pipeline: prepare_training forces
    augment off for the fixed val draw, then restores it."""

    class AugRecordingDataset(SyntheticDataset):
        def __init__(self):
            super().__init__(nsamples=64, nclasses=10, shape=(4, 4, 3))
            self.augment = True
            self.augment_during_batch = []

        def batch(self, rng, n, indices=None):
            self.augment_during_batch.append(self.augment)
            return super().batch(rng, n, indices)

    ds = SyntheticDataset(nsamples=64, nclasses=10, shape=(4, 4, 3))
    val = AugRecordingDataset()
    task = prepare_training(
        SimpleCNN(num_classes=10), ds, optim.momentum(0.1, 0.9),
        mesh=mesh, batch_size=8, cycles=1, val_dataset=val, val_samples=8,
        input_shape=(8, 4, 4, 3),
    )
    assert task.val_batch is not None
    assert val.augment_during_batch == [False]  # draw ran unaugmented
    assert val.augment is True  # and the flag was restored


def test_evaluate_never_mutates_shared_augment_flag(mesh):
    """evaluate() must not toggle ``dataset.augment`` in place: a
    concurrent prefetch loader sharing the object would silently draw
    un-augmented TRAIN batches mid-eval.  The eval path gets a shallow
    view instead; the original's flag stays True THROUGHOUT the eval,
    not just after it."""
    from fluxdistributed_tpu.train import evaluate

    observed = []

    class SharedAugDataset(SyntheticDataset):
        def __init__(self):
            super().__init__(nsamples=32, nclasses=4, shape=(8, 8, 3))
            self.augment = True

        def batch(self, rng, n, indices=None):
            # what a concurrent loader holding the ORIGINAL object would
            # see at this moment (note: reads the outer object, not self)
            observed.append(shared.augment)
            return super().batch(rng, n, indices)

    shared = SharedAugDataset()
    task = prepare_training(
        SimpleCNN(num_classes=4), shared, optim.momentum(0.1, 0.9),
        mesh=mesh, batch_size=8, cycles=1, topk=(1,),
    )
    out = evaluate(task, shared, batch_size=16, topk=(1,))
    assert out["samples"] == 32
    assert observed and all(observed), (
        "evaluate() toggled the shared dataset's augment flag in place"
    )
    assert shared.augment is True


def test_evaluate_whole_dataset(mesh):
    """evaluate() aggregates loss/top-k over the full dataset with the
    compiled eval step; sample counts line up; unbounded streams need
    max_batches."""
    import pytest

    from fluxdistributed_tpu.data import SyntheticDataset, SyntheticTextDataset
    from fluxdistributed_tpu.models import SimpleCNN, lm_loss_fn, lm_tiny
    from fluxdistributed_tpu.train import evaluate, prepare_training, train
    from fluxdistributed_tpu.train.logging import NullLogger

    ds = SyntheticDataset(nsamples=128, nclasses=4, shape=(8, 8, 3))
    task = prepare_training(
        SimpleCNN(num_classes=4), ds, optim.momentum(0.1, 0.9),
        mesh=mesh, batch_size=16, cycles=40, topk=(1,),
    )
    train(task, print_every=0, eval_every=0, logger=NullLogger())
    out = evaluate(task, ds, batch_size=32, topk=(1,))
    assert out["samples"] == 128 and out["exact"] is True
    assert 0.0 <= out["top1"] <= 1.0 and np.isfinite(out["loss"])
    # asking for metrics the eval step never compiled must fail loudly
    with pytest.raises(KeyError, match="top-5"):
        evaluate(task, ds, batch_size=32, topk=(1, 5))
    # batch bigger than the dataset: clamp to a shardable size, stay exact
    small = SyntheticDataset(nsamples=24, nclasses=4, shape=(8, 8, 3))
    out_small = evaluate(task, small, batch_size=256, topk=(1,))
    assert out_small["samples"] == 24 and out_small["exact"] is True
    # truncated coverage is honestly flagged
    out_trunc = evaluate(task, ds, batch_size=32, max_batches=1, topk=(1,))
    assert out_trunc["samples"] == 32 and out_trunc["exact"] is False
    # a size indivisible by the data axis rounds DOWN to a shardable one
    # instead of failing inside shard_batch mid-eval
    n_axis = task.mesh.shape["data"]
    out_odd = evaluate(task, ds, batch_size=n_axis * 4 + 1, topk=(1,))
    assert out_odd["samples"] % n_axis == 0 and out_odd["samples"] > 0
    with pytest.raises(ValueError, match="rounds down"):
        evaluate(task, ds, batch_size=n_axis - 1, topk=(1,))
    # a trailing remainder runs as one extra smaller batch: 104 samples
    # at batch 32 = 3 full batches + 8-sample remainder, nothing dropped
    rem_ds = SyntheticDataset(nsamples=104, nclasses=4, shape=(8, 8, 3))
    out_rem = evaluate(task, rem_ds, batch_size=32, topk=(1,))
    assert out_rem["samples"] == 104 and out_rem["exact"] is True
    assert out_rem["dropped"] == 0
    # only a sub-n_axis tail (101 = 96 + 5 with n_axis=8) is unreachable
    tail_ds = SyntheticDataset(nsamples=101, nclasses=4, shape=(8, 8, 3))
    out_tail = evaluate(task, tail_ds, batch_size=32, topk=(1,))
    assert out_tail["samples"] == 96 and out_tail["dropped"] == 5
    # trained on a learnable task -> much better than the 25% chance floor
    assert out["top1"] > 0.8, out

    lm = lm_tiny(vocab=16, dtype=np.float32)
    tds = SyntheticTextDataset(vocab=16, seqlen=16)
    lm_task = prepare_training(
        lm, tds, optim.adam(1e-3), mesh=mesh, batch_size=16, cycles=1,
        loss_fn=lm_loss_fn(lm), topk=(),
    )
    with pytest.raises(ValueError, match="max_batches"):
        evaluate(lm_task, tds, batch_size=16, topk=())
    out = evaluate(lm_task, tds, batch_size=16, max_batches=2, topk=())
    assert out["samples"] == 32 and out["exact"] is False
    assert np.isfinite(out["loss"])
    # the SAMPLED path (no `indices` support) must round an indivisible
    # batch_size down too, not crash in shard_batch mid-eval
    out_odd = evaluate(lm_task, tds, batch_size=17, max_batches=1, topk=())
    assert out_odd["samples"] == 16


def test_evaluate_exact_lm_corpus(mesh, tmp_path):
    """ByteTextDataset's indices protocol makes LM evaluation exact:
    every non-overlapping window of the corpus is scored once."""
    from fluxdistributed_tpu.data import ByteTextDataset
    from fluxdistributed_tpu.models import lm_loss_fn, lm_tiny
    from fluxdistributed_tpu.train import evaluate

    p = tmp_path / "corpus.txt"
    p.write_bytes(b"x" * (16 * 104))  # exactly 104 windows
    ds = ByteTextDataset(str(p), seqlen=16)
    lm = lm_tiny(vocab=256, dtype=np.float32)
    task = prepare_training(
        lm, ds, optim.adam(1e-3), mesh=mesh, batch_size=16, cycles=1,
        loss_fn=lm_loss_fn(lm), topk=(),
    )
    out = evaluate(task, ds, batch_size=32, topk=())
    # 104 windows: 3 full 32-batches + one 8-window remainder batch
    assert out["exact"] is True
    assert out["samples"] == 104 and out["dropped"] == 0
    assert np.isfinite(out["loss"]) and out["loss"] > 0
    with pytest.raises(IndexError, match="window indices"):
        ds.batch(np.random.default_rng(0), 1, indices=[-1])
