#!/usr/bin/env python
"""Measured pipeline-schedule scaling vs the (S-1)/(M+S-1) formula,
GPipe (AD-derived backward) vs hand-scheduled 1F1B vs zero-bubble —
with modeled-vs-measured bubble accounting from a cost-profile
artifact, and planner-paired rows.

The GPipe schedule (parallel/pp.py:26-28) predicts utilization
M/(M+S-1) for M microbatches over S stages.  This script times the
pipelined LM forward+backward at M in {S, 2S, 4S, 8S} for either
schedule (``--schedule gpipe|1f1b|zb``) and reports per-microbatch
cost scaling (VERDICT r3 weak #6).

Pairings (ROADMAP item 4's planner loop, both modeled AND measured):

* ``--plan auto|PATH`` adds a PLANNED row sweep next to the uniform
  one — same schedule, stage boundaries from the profile-guided
  planner (``parallel/pp_plan.py``; 'auto' plans from fresh static
  costs, PATH loads a profile artifact or saved plan) — so the
  planned-vs-uniform bubble delta is measured, not just modeled;
* ``--with-zb`` (with ``--schedule 1f1b``) adds a zero-bubble row
  sweep — the 1f1b-vs-zb pairing on identical data and params.

Bubble accounting: the run stages out the model for per-layer static
costs (``obs.profile.lm_layer_costs``), fits each configuration's
measured rows to separate steady per-microbatch cost from fixed
fill/drain overhead, and reports the MODELED bubble fraction (schedule
formula over the static per-stage costs at that configuration's
boundaries) next to the MEASURED one per row
(``obs.profile.bubble_report`` — rows are tagged ``schedule``/
``boundaries`` and fitted per group).  ``--profile-out`` persists
everything as a versioned, topology-fingerprinted Profile artifact;
``--profile`` replays the report from a saved artifact without timing
anything (rejecting cross-topology artifacts unless
``--allow-mismatch``).

What each substrate can show:

* a real multi-chip slice measures the BUBBLE itself (idle devices);
* the shared-core fake-device mesh cannot (devices are never idle),
  but it exposes the schedules' MEMORY behavior: GPipe's AD-through-
  scan stores residuals for all M microbatches, so per-tick cost
  inflates with M (cache/allocator pressure), while 1F1B's fixed
  min(S,M)-slot input ring keeps per-microbatch cost ~flat — that
  contrast is the point of the comparison here.  The measured-bubble
  column follows suit: on real chips it is idle time, on the CPU mesh
  it is the schedule's fixed-overhead fraction.  The zb schedule in
  particular trades MORE ticks (3 cheap vs 2 expensive per microbatch)
  for near-zero idle — a win where devices idle, pure overhead on the
  never-idle CPU mesh (docs/parallelism.md spells out the caveat).

    python benchmarks/pp_bubble.py --platform cpu --dim 128 --depth 8 \
        --schedule 1f1b --plan auto --with-zb --profile-out pp_profile.json
    python benchmarks/pp_bubble.py --platform cpu --profile pp_profile.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def report_from_artifact(args) -> None:
    """``--profile``: modeled-vs-measured bubble report from a saved
    artifact — no timing run, no model build."""
    from fluxdistributed_tpu.obs.profile import (
        Profile, ProfileMismatch, bubble_report,
    )

    prof = Profile.load(args.profile)
    if args.allow_mismatch:
        print(json.dumps({"note": "fingerprint check skipped "
                                  "(--allow-mismatch)",
                          "artifact_topology": prof.topology}))
    else:
        # rebuild the artifact's recorded topology so the fingerprint
        # recipe can match; a box that cannot reproduce it is exactly
        # the cross-topology case the check exists to reject
        if args.platform == "cpu":
            from fluxdistributed_tpu.mesh import force_host_devices

            force_host_devices(int(prof.topology.get(
                "device_count", args.devices)))
        from fluxdistributed_tpu.mesh import make_mesh

        try:
            mesh_shape = prof.topology.get("mesh") or {}
            prof.verify(make_mesh({k: int(v) for k, v in
                                   mesh_shape.items()}) if mesh_shape
                        else None)
        except (ProfileMismatch, ValueError) as e:
            raise SystemExit(
                f"{e}\n(pass --allow-mismatch to analyze anyway)")
    rows = bubble_report(prof)
    for r in rows:
        print(json.dumps(r), flush=True)
    print(json.dumps({
        "metric": "pp bubble fraction, modeled vs measured "
                  f"(from {args.profile})",
        "schedule": prof.meta.get("schedule"),
        "rows": rows,
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--devices", type=int, default=8,
                    help="pipe-axis size when forcing the cpu platform")
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--depth", type=int, default=8, help="decoder blocks (= stages)")
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--seqlen", type=int, default=128)
    ap.add_argument("--mb-size", type=int, default=4,
                    help="sequences per microbatch (fixed; M scales total batch)")
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--seconds", type=float, default=2.0)
    ap.add_argument("--schedule", choices=("gpipe", "1f1b", "zb"),
                    default="gpipe")
    ap.add_argument("--remat", action="store_true",
                    help="gpipe only: lm_pp(remat=True) — per-tick input "
                         "checkpointing, the AD-side answer to the residual "
                         "blowup (compare against the 1f1b rows)")
    ap.add_argument("--plan", default=None, metavar="auto|PATH",
                    help="pair every row sweep with a PLANNED one: stage "
                         "boundaries from the profile-guided planner "
                         "('auto' = fresh static costs; PATH = profile "
                         "artifact or saved plan JSON) next to the "
                         "uniform split — measured planned-vs-uniform")
    ap.add_argument("--with-zb", action="store_true",
                    help="with --schedule 1f1b: add a zero-bubble row "
                         "sweep — measured 1f1b-vs-zb on identical data")
    ap.add_argument("--profile-out", default=None, metavar="PATH",
                    help="persist this run (static per-layer costs + "
                         "measured rows + topology fingerprint) as an "
                         "obs.profile artifact the planner / a later "
                         "--profile replay consumes")
    ap.add_argument("--profile", default=None, metavar="PATH",
                    help="skip the timing run: print the modeled-vs-"
                         "measured bubble report from this saved "
                         "artifact (topology-checked)")
    ap.add_argument("--allow-mismatch", action="store_true",
                    help="with --profile or --plan PATH: analyze an "
                         "artifact recorded on a DIFFERENT topology "
                         "(numbers then describe that topology, not "
                         "this box)")
    args = ap.parse_args()
    if args.remat and args.schedule != "gpipe":
        ap.error("--remat applies to --schedule gpipe only (1f1b always "
                 "recomputes from its input ring)")
    if args.with_zb and args.schedule != "1f1b":
        ap.error("--with-zb pairs the zero-bubble schedule against "
                 "--schedule 1f1b rows")
    if args.profile:
        report_from_artifact(args)
        return

    import jax

    if args.platform == "cpu":
        from fluxdistributed_tpu.mesh import force_host_devices

        force_host_devices(args.devices)
    import jax.numpy as jnp

    from fluxdistributed_tpu import mesh as mesh_lib
    from fluxdistributed_tpu.models.transformer_lm import (
        TransformerLM, lm_pp, lm_pp_1f1b,
    )

    S = jax.device_count()
    if S < 2:
        raise SystemExit(
            f"pipeline benchmarking needs >= 2 devices, got {S} — on a "
            "single-chip target there is no pipe axis to schedule over "
            "(CPU: pass --platform cpu --devices N)")
    mesh = mesh_lib.make_mesh({"pipe": S})
    model = TransformerLM(
        vocab=args.vocab, dim=args.dim, depth=args.depth,
        num_heads=args.heads, mlp_dim=4 * args.dim,
        dtype=jnp.float32, dropout=0.0,
    )
    rng = np.random.default_rng(0)
    toks1 = rng.integers(0, args.vocab, (args.mb_size, args.seqlen)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks1, train=False)["params"]

    # ---- planner pairing: resolve the planned boundaries once (they
    # depend on costs, not M); rows then sweep uniform AND planned.
    # The planning M (2S) is the sweep's second row; boundaries are
    # M-independent so any in-range choice models the same placement.
    plan = None
    if args.plan:
        from fluxdistributed_tpu.obs.profile import ProfileMismatch
        from fluxdistributed_tpu.parallel.pp_plan import (
            PlanError, resolve_plan,
        )

        try:
            plan = resolve_plan(
                args.plan, S, 2 * S,
                schedule="zb" if args.schedule == "zb" else "1f1b",
                model=model,
                # full batch at the planning M: the planner divides by
                # M itself for the activation-ring estimate
                batch_size=args.mb_size * 2 * S,
                seqlen=args.seqlen,
                verify=not args.allow_mismatch)
        except (PlanError, ProfileMismatch, ValueError, OSError) as e:
            raise SystemExit(
                f"--plan {args.plan}: {e}\n(pass --allow-mismatch to "
                "analyze a foreign artifact anyway)")
        print(json.dumps({"plan": plan.describe(),
                          "boundaries": list(plan.boundaries)}), flush=True)
        if plan.is_uniform:
            # a real result, not a sweep: the planner confirms uniform
            # placement is optimal here — don't burn wall time (on a
            # chip: grant time) measuring bit-identical configurations
            print(json.dumps({
                "note": "plan is UNIFORM for this model/topology "
                        "(planned rows skipped — they would duplicate "
                        "the uniform sweep)",
                "modeled_bubble": plan.modeled_bubble}), flush=True)

    planned_bounds = (plan.boundaries
                      if plan is not None and not plan.is_uniform else None)
    configs = [(args.schedule, None)]
    if planned_bounds is not None:
        configs.append((args.schedule, planned_bounds))
    if args.with_zb:
        configs.append(("zb", None))
        if planned_bounds is not None:
            configs.append(("zb", planned_bounds))

    rows = []
    for sched, bounds in configs:
        # every configuration times IDENTICAL token batches (the
        # pairing promise): re-seed per config so the M-sweep draws
        # the same sequence each time
        rng = np.random.default_rng(1)
        base_per_mb = None
        # zb runs 3 cheap ticks per microbatch where 1f1b runs 2
        # expensive ones; its fill/drain term is one third
        drain = (S - 1) / 3.0 if sched == "zb" else float(S - 1)
        for mult in (1, 2, 4, 8):
            M = S * mult
            batch = args.mb_size * M
            toks = rng.integers(
                0, args.vocab, (batch, args.seqlen)).astype(np.int32)
            if sched == "gpipe":
                split_params, loss_fn, _ = lm_pp(
                    model, mesh, num_microbatches=M, remat=args.remat,
                    boundaries=bounds)
                pp = split_params(params)

                @jax.jit
                def fwdbwd(p, t):
                    # loss on the pipelined forward; grads run the
                    # reverse schedule
                    def loss(pp_):
                        l, _aux = loss_fn(pp_, {}, {"tokens": t}, False)
                        return l

                    return jax.value_and_grad(loss)(p)

            else:
                from fluxdistributed_tpu.parallel.pp_1f1b import (
                    pipeline_grads_1f1b,
                )

                w = lm_pp_1f1b(model, mesh, boundaries=bounds)
                pp = w.split_params(params)
                run = pipeline_grads_1f1b(
                    *w.fns, mesh, num_microbatches=M,
                    interleave=w.interleave, schedule=sched)

                @jax.jit
                def fwdbwd(p, t):
                    # the 1F1B/zb program IS fwd+bwd: loss + both grad
                    # trees
                    return run(p["stages"], p["outer"], t, t)

            l, *g = fwdbwd(pp, toks)
            jax.block_until_ready(l)
            t0 = time.perf_counter()
            iters = 0
            while time.perf_counter() - t0 < args.seconds:
                l, *g = fwdbwd(pp, toks)
                iters += 1
            jax.block_until_ready(l)
            dt = (time.perf_counter() - t0) / iters
            per_mb = dt / M
            if base_per_mb is None:
                base_per_mb = per_mb  # M=S row anchors the comparison
            util_pred = M / (M + drain)
            # measured utilization relative to the M=S anchor's
            # prediction for THIS schedule's drain term
            util_meas = (base_per_mb / per_mb) * (S / (S + drain))
            row = {
                "M": M, "S": S, "batch": batch,
                "schedule": sched,
                "step_ms": round(dt * 1e3, 2),
                "ms_per_microbatch": round(per_mb * 1e3, 3),
                "util_formula": round(util_pred, 4),
                "util_measured": round(util_meas, 4),
            }
            if bounds is not None:
                row["boundaries"] = list(bounds)
            rows.append(row)
            print(json.dumps(row), flush=True)

    pairings = []
    if planned_bounds is not None:
        pairings.append("planned-vs-uniform")
    if args.with_zb:
        pairings.append("1f1b-vs-zb")
    print(json.dumps({
        "metric": f"{args.schedule}{'-remat' if args.remat else ''} "
                  "pipeline: measured vs M/(M+drain)"
                  + (f" [{', '.join(pairings)}]" if pairings else ""),
        "platform": jax.devices()[0].platform,
        "rows": rows,
    }))

    # ---- modeled vs measured bubble accounting (obs.profile) ----------
    # Static per-layer costs from the STAGED-OUT model (forward FLOPs;
    # fwd+bwd scales every block ~uniformly, so the stage-cost RATIOS
    # the schedule model needs are preserved) + the measured rows above,
    # bundled as the topology-fingerprinted artifact the planner reads.
    from fluxdistributed_tpu.compilation import topology_fingerprint
    from fluxdistributed_tpu.obs.profile import (
        Profile, bubble_report, describe_topology, lm_layer_costs,
    )

    prof = Profile(
        fingerprint=topology_fingerprint(mesh=mesh),
        topology=describe_topology(mesh),
        static={"model": lm_layer_costs(model, args.mb_size, args.seqlen),
                "step": None, "variants": {}},
        measured={"pp_rows": rows},
        meta={"schedule": args.schedule, "remat": bool(args.remat),
              "mb_size": args.mb_size, "seqlen": args.seqlen,
              "vocab": args.vocab, "producer": "benchmarks/pp_bubble.py",
              "with_zb": bool(args.with_zb),
              "plan_boundaries": (list(plan.boundaries)
                                  if plan is not None else None)},
    )
    if args.profile_out:
        prof.save(args.profile_out)
        print(json.dumps({"profile_artifact": args.profile_out,
                          "fingerprint": prof.fingerprint}), flush=True)
    breport = bubble_report(prof)
    print(json.dumps({
        "metric": f"{args.schedule} pp bubble fraction, modeled "
                  "(static per-stage costs through the schedule model) "
                  "vs measured (fixed-cost share of wall time, fitted "
                  "per schedule/boundaries group)",
        "platform": jax.devices()[0].platform,
        "rows": breport,
    }))


if __name__ == "__main__":
    main()
