"""FDT107 positive: a step factory documenting donation whose jit calls
never declare it."""
import jax


def make_toy_step(loss_fn, donate=True):
    """Build the compiled step.  Donates the incoming state when
    ``donate=True`` so buffers are updated in place."""

    def step(state, batch):
        return state

    return jax.jit(step)  # the docstring's promise is never kept
