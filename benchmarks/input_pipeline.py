#!/usr/bin/env python
"""Input-pipeline throughput benchmark: native C++ ingest vs PIL.

The native loader's reason to exist is feeding the compiled train step
(≥2,270 img/s/chip for ResNet-50 on v5e — see docs/benchmarks.md); this
script measures what the host side can actually deliver: JPEG decode +
resize-256/center-crop-224/normalize throughput for

* the native C++ thread-pool pipeline (``native.load_batch``),
* the PIL/numpy fallback path (``ImageNetDataset`` with
  ``use_native=False``),

across thread counts, on a generated fixture tree of ImageNet-sized
JPEGs (500x375, the ILSVRC median).  The reference's analog is its
threaded ``minibatch`` ingest (one Julia task per image,
src/imagenet.jl:44-46), which it never measured either (SURVEY §6).

Usage:  python benchmarks/input_pipeline.py [--images N] [--batch N]
                                            [--threads 1,2,4,8]
Prints a table plus one JSON line for regression tracking.
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

import numpy as np


def make_fixture(root: str, n_images: int, size=(500, 375)) -> list[str]:
    from PIL import Image

    rng = np.random.default_rng(0)
    paths = []
    d = os.path.join(root, "imgs")
    os.makedirs(d, exist_ok=True)
    for i in range(n_images):
        # low-frequency content so JPEG size/entropy is realistic-ish;
        # kron up PAST the target then crop to exactly size
        base = rng.normal(0, 1, (8, 8, 3))
        arr = np.kron(base, np.ones((-(-size[1] // 8), -(-size[0] // 8), 1)))
        arr = ((arr - arr.min()) / (np.ptp(arr) + 1e-9) * 255).astype(np.uint8)
        arr = arr[: size[1], : size[0]]
        assert arr.shape[:2] == (size[1], size[0]), arr.shape
        p = os.path.join(d, f"img_{i:05d}.jpg")
        Image.fromarray(arr).save(p, quality=85)
        paths.append(p)
    return paths


def bench_native(paths, batch, threads, seconds=3.0):
    from fluxdistributed_tpu.data import native

    idx = np.random.default_rng(0).integers(0, len(paths), batch)
    sel = [paths[i] for i in idx]
    native.load_batch(sel, num_threads=threads)  # warmup
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < seconds:
        native.load_batch(sel, num_threads=threads)
        n += batch
    return n / (time.perf_counter() - t0)


def bench_pil(paths, batch, threads, seconds=3.0):
    from concurrent.futures import ThreadPoolExecutor

    from fluxdistributed_tpu.data.preprocess import preprocess

    idx = np.random.default_rng(0).integers(0, len(paths), batch)
    sel = [paths[i] for i in idx]
    pool = ThreadPoolExecutor(max_workers=threads)

    def run_once():
        list(pool.map(preprocess, sel))

    run_once()  # warmup
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < seconds:
        run_once()
        n += batch
    rate = n / (time.perf_counter() - t0)
    pool.shutdown(wait=False)
    return rate


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=256)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--threads", default="1,2,4,8")
    ap.add_argument("--seconds", type=float, default=3.0)
    args = ap.parse_args()
    threads = [int(t) for t in args.threads.split(",")]

    from fluxdistributed_tpu.data import native

    with tempfile.TemporaryDirectory() as root:
        paths = make_fixture(root, args.images)
        print(f"fixture: {len(paths)} JPEGs 500x375, batch {args.batch}, "
              f"host cpus {os.cpu_count()}")
        has_native = native.available()
        if not has_native:
            print("native loader unavailable (g++/libjpeg missing) — PIL only")
        rows = []
        for t in threads:
            nat = bench_native(paths, args.batch, t, args.seconds) if has_native else None
            pil = bench_pil(paths, args.batch, t, args.seconds)
            rows.append((t, nat, pil))
            nat_s = f"{nat:8.1f}" if nat is not None else "     n/a"
            ratio = f"{nat / pil:5.2f}x" if (nat and pil) else "  n/a"
            print(f"threads {t:2d}: native {nat_s} img/s   PIL {pil:8.1f} img/s   {ratio}")

        best_native = max((r[1] for r in rows if r[1]), default=None)
        best_pil = max(r[2] for r in rows)
        print(json.dumps({
            "metric": "input-pipeline decode+preprocess throughput",
            "unit": "images/sec",
            "native_best": round(best_native, 1) if best_native else None,
            "pil_best": round(best_pil, 1),
            "host_cpus": os.cpu_count(),
            "threads": threads,
        }))


if __name__ == "__main__":
    main()
