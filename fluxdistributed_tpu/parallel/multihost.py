"""Multi-host (multi-process) distributed runtime.

TPU-native replacement for the reference's process-DDP layer
(src/sync.jl + bin/driver.jl): where the reference spawns one Julia
worker process per GPU (``addprocs(4)`` bin/driver.jl:3), moves
gradients worker→hub over capacity-1 ``RemoteChannel``s with CPU
serialization (``syncgrads`` src/sync.jl:36-81, worker side :145-149),
and lock-steps every batch on the hub's average, here:

* one OS process per TPU host joins a *global* JAX runtime via
  ``jax.distributed.initialize`` (PJRT owns the transport);
* ``jax.devices()`` then enumerates every chip in the pod slice, so the
  SAME compiled SPMD train step used single-host spans all hosts — the
  gradient all-reduce rides ICI within a slice and DCN across slices.
  There is no hub, no serialization, and no second code path: the
  process-DDP/task-DDP split of the reference collapses into one
  program;
* per-host input feeding goes through
  ``jax.make_array_from_process_local_data`` — each host assembles only
  its rows of the global batch (the analog of each reference worker
  sampling its own minibatch, src/sync.jl:135);
* the reference's cooperative abort — every worker ``put!``s ``nothing``
  to end ``syncgrads`` (src/sync.jl:49-53) — becomes ``agree_to_stop``,
  an all-gather of per-process stop flags.

The same module drives the CPU fake-cluster used in tests: N processes
x M virtual CPU devices with gloo collectives (see
tests/test_multihost.py), mirroring how the reference tests its
machinery without GPUs (test/single_device.jl:121-151).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import mesh as mesh_lib

Pytree = Any

__all__ = [
    "initialize",
    "is_distributed",
    "process_index",
    "process_count",
    "is_coordinator",
    "local_batch_size",
    "global_batch",
    "global_batch_put",
    "host_local_values",
    "broadcast_from_coordinator",
    "sync_global_devices",
    "agree_to_stop",
    "commit_to_mesh",
]


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    platform: Optional[str] = None,
    local_devices: Optional[int] = None,
) -> None:
    """Join (or form) the global distributed runtime.

    On a real TPU pod each host calls this with no arguments — JAX
    auto-detects the cluster from the TPU metadata (the analog of the
    reference's ``addprocs`` + driver bring-up, bin/driver.jl:3-23,
    minus the manual channel plumbing).  On CPU (tests, dev boxes) pass
    the coordinator address/world explicitly and optionally
    ``platform="cpu"`` + ``local_devices=N`` for an N-virtual-device
    fake host; CPU cross-process collectives go through gloo.

    Must run before any JAX backend initializes (this image pre-imports
    jax, so the platform override goes through ``jax.config``).
    """
    if platform is not None:
        jax.config.update("jax_platforms", platform)
    if local_devices is not None:
        try:
            jax.config.update("jax_num_cpu_devices", int(local_devices))
        except AttributeError:
            # pre-0.5 jax: the option doesn't exist — the XLA flag is the
            # same knob read at backend init (we run before that).  A
            # pre-set count must be REWRITTEN, not kept: the caller's
            # request wins over e.g. a CI harness's stale pin.
            import re as _re

            flag = f"--xla_force_host_platform_device_count={int(local_devices)}"
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" in flags:
                flags = _re.sub(
                    r"--xla_force_host_platform_device_count=\d+", flag, flags
                )
            else:
                flags = (flags + " " + flag).strip()
            os.environ["XLA_FLAGS"] = flags
    plat = platform or os.environ.get("JAX_PLATFORMS", "")
    if "cpu" in plat and (num_processes is not None or coordinator_address):
        # cross-process CPU collectives only; a single process needs no
        # transport (and pre-0.5 jaxlib rejects gloo without a
        # distributed client)
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    if num_processes is None and coordinator_address is None:
        # single-process / auto-detected TPU environment
        try:
            jax.distributed.initialize()
        except (ValueError, RuntimeError) as e:
            # Only a genuinely-absent cluster environment may fall back to
            # single-process; anything else (coordinator timeout, partial
            # metadata) must surface — a silent fallback would let one pod
            # host train a private model while the rest form a smaller
            # world.
            msg = str(e).lower()
            if "coordinator_address" in msg or "cluster" in msg or "environment" in msg:
                import warnings

                warnings.warn(
                    f"no distributed cluster detected ({e}); running single-process"
                )
            else:
                raise
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def is_distributed() -> bool:
    return jax.process_count() > 1


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """Process 0 — the analog of the reference's hub process 1
    (``syncgrads`` runs there, src/sync.jl:36), except no reduction work
    happens here: it only owns logging/checkpoint naming."""
    return jax.process_index() == 0


def local_batch_size(global_batch_size: int) -> int:
    """Rows of the global batch this host must supply."""
    n = jax.process_count()
    if global_batch_size % n:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by {n} processes"
        )
    return global_batch_size // n


def global_batch(
    local: Pytree,
    mesh: Mesh,
    axis: str = mesh_lib.DATA_AXIS,
) -> Pytree:
    """Assemble a globally-sharded batch from per-process local rows.

    Each process passes its own ``local`` arrays (leading dim =
    global/process_count); the result is a pytree of global
    ``jax.Array``s sharded ``P(axis)`` across the whole mesh.  This is
    the data-ingest boundary that replaces the reference workers'
    per-process ``gpu(minibatch(...))`` (src/sync.jl:135-136) — no
    cross-host copy happens here; every host feeds only its addressable
    shards.
    """
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda x: global_batch_put(x, sharding), local)


def global_batch_put(x, sharding, batch_dim: int = 0) -> jax.Array:
    """Single-leaf version of :func:`global_batch` for callers that already
    hold a NamedSharding — the one canonical local-rows→global-array
    boundary (loader and ``shard_batch`` both route through here).

    ``batch_dim`` names the dim holding this process's local rows
    (default 0; the loader's chunked ``[K, batch, ...]`` layout passes 1).
    """
    x = np.asarray(x)
    nproc = jax.process_count()
    if nproc == 1:
        return jax.device_put(x, sharding)
    global_shape = list(x.shape)
    global_shape[batch_dim] *= nproc
    return jax.make_array_from_process_local_data(sharding, x, tuple(global_shape))


def host_local_values(x) -> np.ndarray:
    """Gather a (possibly sharded) array's global value onto every host —
    the analog of the reference hub's ``take!``/CPU materialization
    (src/sync.jl:43-47), used only at eval/log boundaries."""
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return np.asarray(jax.device_get(x))
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def broadcast_from_coordinator(tree: Pytree) -> Pytree:
    """Broadcast host-side values from process 0 to all processes —
    the analog of the hub's ``put!.(op, f)`` result broadcast
    (src/sync.jl:73-77)."""
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return tree
    return multihost_utils.broadcast_one_to_all(tree)


def sync_global_devices(tag: str = "barrier") -> None:
    """Cross-process barrier — the compiled-world analog of the
    reference's busy-poll ``all(isready, ip)`` barrier (src/sync.jl:41)."""
    from jax.experimental import multihost_utils

    if jax.process_count() > 1:
        multihost_utils.sync_global_devices(tag)


def commit_to_mesh(x, like) -> jax.Array:
    """Commit a HOST array to the sharding of ``like`` (a device array,
    a ``NamedSharding``, or anything exposing ``.sharding``) — the
    elastic-resume boundary: a checkpoint saved on one topology is
    restored to host arrays and re-committed, leaf by leaf, to the NEW
    mesh's shardings (``train.checkpoint.load_checkpoint_elastic``).

    Multi-host safe: each process materializes only its addressable
    shards (``jax.make_array_from_callback`` slices the host copy per
    shard), so a replicated-everywhere host value never round-trips
    through a single device.
    """
    from jax.sharding import Sharding

    sharding = like if isinstance(like, Sharding) else getattr(
        like, "sharding", None)
    x = np.asarray(x)
    if sharding is None:
        return jax.device_put(x)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_callback(
        x.shape, sharding, lambda idx: x[idx])


def agree_to_stop(local_stop: bool) -> bool:
    """Cooperative abort: True iff ANY process wants to stop.

    The reference ends training when every worker ``put!``s ``nothing``
    into its gradient channel (src/sync.jl:49-53).  Here each process
    contributes a flag; any True stops everyone at the same step, so no
    process hangs in a collective the others never enter.
    """
    if jax.process_count() == 1:
        return bool(local_stop)
    flags = host_local_values(np.asarray([bool(local_stop)]))
    return bool(np.any(flags))
