"""The jaxpr-layer check targets: every registered train-step variant +
the serve engine's compiled program pool, built tiny on the 8-virtual-
device CPU mesh.

Each entry goes through the REAL registered path — ``prepare_training``
for the parallelism modes, ``LMEngine`` for serving — with toy model
sizes, so what the static layer validates is exactly the code a real run
compiles: the step factories, the sharding layouts, the donation
vectors.  Nothing here executes a step by default (building a variant
traces nothing); the jaxpr checks lower/abstract-eval the returned
callables on CPU in seconds where a hardware bench round would burn
minutes discovering the same bug.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["StepVariant", "VARIANT_BUILDERS", "variant_names", "build_variants"]


@dataclasses.dataclass
class StepVariant:
    """One compiled-program check target.

    ``fn(*args)`` is the jit-wrapped program; ``donate_argnums`` is what
    the variant DECLARES it donates (the jaxpr layer verifies the
    declaration is consumable); ``source`` is the repo-relative file of
    the factory the findings should point at.  ``execute=True`` marks
    the variant cheap enough for the optional transfer-guard execution
    check (one real compiled step on CPU)."""

    name: str
    fn: Callable
    args: Tuple[Any, ...]
    donate_argnums: Tuple[int, ...]
    mesh: Any
    source: str
    execute: bool = False
    #: thread one call's outputs into the next call's arguments — the
    #: steady-state input for the guarded second call of the transfer
    #: check (required for executing variants that donate buffers)
    carry: Optional[Callable[[Tuple, Any], Tuple]] = None


def _src(module) -> str:
    """Repo-relative path of a module's source file."""
    from .engine import repo_root

    path = os.path.abspath(module.__file__)
    try:
        rel = os.path.relpath(path, repo_root())
    except ValueError:
        rel = path
    return rel.replace(os.sep, "/")


def _image_setup():
    from ..data.synthetic import SyntheticDataset
    from ..models.simple import SimpleCNN

    return (SimpleCNN(num_classes=4, features=8),
            SyntheticDataset(nsamples=64, nclasses=4, shape=(8, 8, 3)))


def _lm_setup(depth: int, heads: int, attn_fn=None):
    import jax.numpy as jnp

    from ..data.synthetic import SyntheticTextDataset
    from ..models.transformer_lm import TransformerLM

    model = TransformerLM(
        vocab=32, dim=16, depth=depth, num_heads=heads, mlp_dim=32,
        dtype=jnp.float32, dropout=0.0, attn_fn=attn_fn)
    return model, SyntheticTextDataset(vocab=32, seqlen=16)


def _prepared(name: str, model, dataset, mesh, source_mod,
              execute: bool = False, **kw) -> List[StepVariant]:
    """Run the real ``prepare_training`` path and wrap its compiled step
    as a check target (donate=True so the donation vector is live)."""
    from .. import optim
    from ..train.trainer import _dummy_batch, prepare_training

    task = prepare_training(
        model, dataset, optim.adam(1e-3), mesh=mesh, batch_size=16,
        cycles=1, donate=True, **kw)
    # the task's batch axes, not a hardcoded one: the 3-D layouts
    # shard batches over (data, fsdp) jointly
    batch = _dummy_batch(dataset, None, 16, mesh, 1, seed=0,
                         axis=task.batch_axes)
    return [StepVariant(
        name=name, fn=task.step_fn, args=(task.state, batch),
        donate_argnums=(0,), mesh=mesh, source=_src(source_mod),
        execute=execute,
        # (state, batch) → ((new_state, metrics)) → (new_state, batch)
        carry=lambda args, out: (out[0], args[1]))]


def _build_dp() -> List[StepVariant]:
    from .. import mesh as mesh_lib
    from ..parallel import dp

    model, ds = _image_setup()
    return _prepared("dp", model, ds, mesh_lib.data_mesh(8), dp,
                     execute=True, spmd="jit")


def _build_zero1() -> List[StepVariant]:
    from .. import mesh as mesh_lib
    from ..parallel import zero1

    model, ds = _image_setup()
    return _prepared("zero1", model, ds, mesh_lib.data_mesh(8), zero1,
                     execute=True, spmd="jit", zero1=True)


def _build_dp_shardmap() -> List[StepVariant]:
    """The explicit-collectives DP step (``spmd="shard_map"``): per-
    device grads + pmean written out as real collective primitives.
    Registered so the comms ledger's jaxpr layer sees DP's semantic
    signature — all-reduce ONLY — on a real ``prepare_training`` path
    (the GSPMD dp variant's jaxpr carries no collectives; XLA inserts
    them at compile time)."""
    from .. import mesh as mesh_lib
    from ..parallel import dp

    model, ds = _image_setup()
    return _prepared("dp_shardmap", model, ds, mesh_lib.data_mesh(8), dp,
                     execute=True, spmd="shard_map")


def _build_zero1_shardmap() -> List[StepVariant]:
    """The explicit-collectives ZeRO-1 step (``spmd="shard_map",
    zero1=True``): reduce-scatter → slice-local update → all-gather,
    the arXiv:2004.13336 schedule written out.  Registered so the
    comms ledger can assert the paper's signature (reduce-scatter +
    all-gather where dp shows all-reduce) on the real path."""
    from .. import mesh as mesh_lib
    from ..parallel import zero1

    model, ds = _image_setup()
    return _prepared("zero1_shardmap", model, ds, mesh_lib.data_mesh(8),
                     zero1, execute=True, spmd="shard_map", zero1=True)


def _build_fsdp() -> List[StepVariant]:
    from .. import mesh as mesh_lib
    from ..parallel import fsdp

    model, ds = _image_setup()
    return _prepared("fsdp", model, ds, mesh_lib.data_mesh(8), fsdp,
                     execute=True, spmd="fsdp")


def _build_tp() -> List[StepVariant]:
    from .. import mesh as mesh_lib
    from ..models.transformer_lm import lm_loss_fn
    from ..parallel import tp

    mesh = mesh_lib.make_mesh(
        {mesh_lib.DATA_AXIS: 2, mesh_lib.MODEL_AXIS: 4})
    model, ds = _lm_setup(depth=1, heads=4)
    return _prepared("tp", model, ds, mesh, tp, spmd="tp",
                     loss_fn=lm_loss_fn(model), topk=())


def _build_pp_1f1b() -> List[StepVariant]:
    from .. import mesh as mesh_lib
    from ..parallel import pp_1f1b

    mesh = mesh_lib.make_mesh(
        {mesh_lib.DATA_AXIS: 2, mesh_lib.PIPE_AXIS: 4})
    model, ds = _lm_setup(depth=4, heads=2)
    return _prepared("pp_1f1b", model, ds, mesh, pp_1f1b,
                     spmd="pp_1f1b", num_microbatches=2, topk=())


def _build_pp_planned() -> List[StepVariant]:
    """The planner-placed pipeline: depth 6 over 4 pipe devices via a
    non-uniform PipelinePlan (counts [1, 2, 2, 1] — padded chunk scan,
    cond-skipped idle chunks, lifted depth-divisibility requirement).  Sweeping
    it proves the counts-aware ``chunk_stages`` program keeps the pp
    invariants: donation consumable, axis hygiene, stable retrace
    digests (the counts table is baked, never an argument)."""
    from .. import mesh as mesh_lib
    from ..parallel import pp_plan as pp_plan_mod

    mesh = mesh_lib.make_mesh(
        {mesh_lib.DATA_AXIS: 2, mesh_lib.PIPE_AXIS: 4})
    model, ds = _lm_setup(depth=6, heads=2)
    # flat block costs + outer weight on the end stages -> the planner
    # thins the first/last stage: boundaries (0, 1, 3, 5, 6)
    plan = pp_plan_mod.plan_stages(
        [1.0] * 6, 4, 2, outer=(1.0, 1.0))
    return _prepared("pp_planned", model, ds, mesh, pp_plan_mod,
                     spmd="pp_1f1b", num_microbatches=2, topk=(),
                     pp_plan=plan)


def _build_pp_zb() -> List[StepVariant]:
    """The zero-bubble schedule (pp_1f1b ``schedule="zb"``): B/W-split
    backward, cot-stash ring riding the scan carry.  Swept so the W
    tick's cond branches and the extra carry keep donation/axis/retrace
    hygiene."""
    from .. import mesh as mesh_lib
    from ..parallel import pp_1f1b

    mesh = mesh_lib.make_mesh(
        {mesh_lib.DATA_AXIS: 2, mesh_lib.PIPE_AXIS: 4})
    model, ds = _lm_setup(depth=4, heads=2)
    return _prepared("pp_zb", model, ds, mesh, pp_1f1b,
                     spmd="pp_1f1b", num_microbatches=2, topk=(),
                     pipeline_schedule="zb")


def _build_layout_dp_fsdp() -> List[StepVariant]:
    """The rule-derived 2-D layout (dp=2 x fsdp=4) on the image model:
    the EMPTY rule table + the ShardLargest fsdp overlay shards a conv
    stack with no per-model spec code — swept so the 3-D mesh step
    keeps donation/axis/retrace hygiene like the hand-built fsdp
    variant it generalizes."""
    from .. import mesh as mesh_lib  # noqa: F401 — axis constants source
    from ..parallel import layout as layout_mod

    model, ds = _image_setup()
    lay = layout_mod.resolve_layout("dp_fsdp", 8)
    return _prepared("layout_dp_fsdp", model, ds, lay.build_mesh(),
                     layout_mod, execute=True, layout=lay)


def _build_layout_fsdp_tp() -> List[StepVariant]:
    """fsdp=4 x tp=2 on the LM: the committed lm_tp rule table decides
    the Megatron dims, the overlay ZeRO-shards the leftovers — the 2-D
    large-model recipe, derived from data instead of
    hybrid_fsdp_tp_specs' special case."""
    from ..models.transformer_lm import lm_loss_fn
    from ..parallel import layout as layout_mod

    model, ds = _lm_setup(depth=1, heads=4)
    lay = layout_mod.resolve_layout("fsdp_tp", 8)
    return _prepared("layout_fsdp_tp", model, ds, lay.build_mesh(),
                     layout_mod, layout=lay,
                     loss_fn=lm_loss_fn(model), topk=())


def _build_layout_dp_fsdp_tp() -> List[StepVariant]:
    """The full 3-D composition dp=2 x fsdp=2 x tp=2 — one mesh, one
    rule table, all three parallelism families at once (the
    arXiv:1810.09868 full-program partitioning thesis, exercised on
    the real prepare_training path)."""
    from ..models.transformer_lm import lm_loss_fn
    from ..parallel import layout as layout_mod

    model, ds = _lm_setup(depth=1, heads=4)
    lay = layout_mod.resolve_layout("dp_fsdp_tp", 8)
    return _prepared("layout_dp_fsdp_tp", model, ds, lay.build_mesh(),
                     layout_mod, layout=lay,
                     loss_fn=lm_loss_fn(model), topk=())


def _build_context() -> List[StepVariant]:
    from .. import mesh as mesh_lib
    from ..models.transformer_lm import lm_loss_fn
    from ..parallel import context

    mesh = mesh_lib.make_mesh(
        {mesh_lib.DATA_AXIS: 2, mesh_lib.SEQ_AXIS: 4})
    model, ds = _lm_setup(
        depth=1, heads=4,
        attn_fn=context.make_ring_attention(
            mesh, batch_axis=mesh_lib.DATA_AXIS, causal=True))
    return _prepared("context", model, ds, mesh, context, spmd="sp",
                     loss_fn=lm_loss_fn(model), topk=())


def _build_serve() -> List[StepVariant]:
    """The engine's per-program pool: one prefill per bucket, the slot
    splice, the all-slot decode step — with the donation vectors the
    engine declares (cache/token/key state updated in place)."""
    import jax

    from ..serve import engine as engine_mod

    model, _ = _lm_setup(depth=1, heads=2)
    params = model.init(jax.random.PRNGKey(0),
                        jax.numpy.zeros((1, 8), "int32"), train=False)["params"]
    eng = engine_mod.LMEngine(model, params, max_slots=2, max_len=64,
                              buckets=(16, 32))
    src = _src(engine_mod)
    out = [
        StepVariant(name="serve:step", fn=eng._step_jit,
                    args=eng._example_args("step"),
                    donate_argnums=(1, 2, 4), mesh=None, source=src,
                    # (params, cache, tok, temp, keys) → (cache', tok', keys')
                    carry=lambda a, o: (a[0], o[0], o[1], a[3], o[2])),
        StepVariant(name="serve:insert", fn=eng._insert_jit,
                    args=eng._example_args("insert"),
                    donate_argnums=(0,), mesh=None, source=src,
                    # (big, small, slot, plen) → spliced big cache
                    carry=lambda a, o: (o, a[1], a[2], a[3])),
    ]
    for b in eng.buckets:
        out.append(StepVariant(
            name=f"serve:prefill_b{b}", fn=eng._prefill_jit,
            args=eng._example_args("prefill", b),
            donate_argnums=(), mesh=None, source=src))
    return out


def _build_serve_paged() -> List[StepVariant]:
    """The paged-layout engine's program pool: the all-slot decode step,
    the prefill chunk, and the page-table maintenance programs (bind /
    release) — with the donation vectors the engine declares.  Page
    indirection must stay DATA: the jaxpr checks verify the pool's
    retrace digests are stable, i.e. page-table churn compiles nothing."""
    import jax

    from ..serve import engine as engine_mod

    model, _ = _lm_setup(depth=1, heads=2)
    params = model.init(jax.random.PRNGKey(0),
                        jax.numpy.zeros((1, 8), "int32"), train=False)["params"]
    eng = engine_mod.LMEngine(model, params, max_slots=2, max_len=64,
                              layout="paged", kv_block_size=8,
                              prefill_chunk=16, prefix_cache=True)
    src = _src(engine_mod)
    return [
        StepVariant(name="serve_paged:step", fn=eng._step_jit,
                    args=eng._example_args("step"),
                    donate_argnums=(1, 2, 4), mesh=None, source=src,
                    # (params, cache, tok, temp, keys) → (cache', tok', keys')
                    carry=lambda a, o: (a[0], o[0], o[1], a[3], o[2])),
        StepVariant(name="serve_paged:chunk", fn=eng._chunk_jit,
                    args=eng._example_args("chunk"),
                    donate_argnums=(1,), mesh=None, source=src,
                    # (params, cache, toks, slot, start, nvalid, arm) →
                    #     (cache', last_logits)
                    carry=lambda a, o: (a[0], o[0]) + a[2:]),
        StepVariant(name="serve_paged:bind", fn=eng._bind_jit,
                    args=eng._example_args("bind"),
                    donate_argnums=(0,), mesh=None, source=src,
                    # (cache, slot, page_row) → cache'
                    carry=lambda a, o: (o,) + a[1:]),
        StepVariant(name="serve_paged:release", fn=eng._release_jit,
                    args=eng._example_args("release"),
                    donate_argnums=(0,), mesh=None, source=src,
                    carry=lambda a, o: (o,) + a[1:]),
    ]


def _build_serve_paged_pallas() -> List[StepVariant]:
    """The paged pool again, but decoding through the Pallas fast path
    with a quantized (int8) KV cache — the kernel-suite configuration
    (ops/pallas_decode.py).  Sweeping it proves the flash-decode branch
    keeps the paged invariants the XLA branch established: donation
    vectors consumable, page indirection pure DATA (stable retrace
    digests — kernel dispatch cannot break AOT keys), axis hygiene."""
    import jax

    from ..serve import engine as engine_mod

    model, _ = _lm_setup(depth=1, heads=2)
    params = model.init(jax.random.PRNGKey(0),
                        jax.numpy.zeros((1, 8), "int32"), train=False)["params"]
    eng = engine_mod.LMEngine(model, params, max_slots=2, max_len=64,
                              layout="paged", kv_block_size=8,
                              prefill_chunk=16, attention_impl="pallas",
                              kv_dtype="int8")
    src = _src(engine_mod)
    return [
        StepVariant(name="serve_paged_pallas:step", fn=eng._step_jit,
                    args=eng._example_args("step"),
                    donate_argnums=(1, 2, 4), mesh=None, source=src,
                    carry=lambda a, o: (a[0], o[0], o[1], a[3], o[2])),
        StepVariant(name="serve_paged_pallas:chunk", fn=eng._chunk_jit,
                    args=eng._example_args("chunk"),
                    donate_argnums=(1,), mesh=None, source=src,
                    carry=lambda a, o: (a[0], o[0]) + a[2:]),
    ]


def _build_zero1_fused() -> List[StepVariant]:
    """The fused packed ZeRO-1 update (parallel/zero1_fused.py): one
    reduce-scatter + one fused Adam kernel + one all-gather inside the
    shard_map — checked for the same donation/axis/retrace invariants
    as the composable zero1 step it accelerates."""
    import jax

    import jax.numpy as jnp

    from .. import mesh as mesh_lib
    from ..ops import logitcrossentropy
    from ..parallel import zero1_fused as zf
    from ..parallel.dp import flax_loss_fn
    from ..sharding import shard_batch

    mesh = mesh_lib.data_mesh(8)
    model, _ = _image_setup()
    x = jnp.zeros((16, 8, 8, 3), jnp.float32)
    y = jnp.zeros((16, 4), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x[:2], train=True)["params"]
    loss_fn = flax_loss_fn(model, logitcrossentropy, has_aux_state=False)
    state, _ = zf.zero1_fused_state(params, mesh)
    step = zf.make_train_step_zero1_fused(
        loss_fn, mesh, state, lr=1e-3, donate=True)
    batch = shard_batch({"image": x, "label": y}, mesh)
    return [StepVariant(
        name="zero1_fused", fn=step, args=(state, batch),
        donate_argnums=(0,), mesh=mesh, source=_src(zf),
        execute=True,
        carry=lambda args, out: (out[0], args[1]))]


#: name → builder; the six parallelism variants the acceptance gate
#: names (plus the explicit-collectives shard_map dp/zero1 pair the
#: comms ledger pins its signatures on), the serve engine's program
#: pools (dense and paged, the paged Pallas/int8 fast path) and the
#: fused ZeRO-1 update
VARIANT_BUILDERS: Dict[str, Callable[[], List[StepVariant]]] = {
    "dp": _build_dp,
    "dp_shardmap": _build_dp_shardmap,
    "zero1": _build_zero1,
    "zero1_shardmap": _build_zero1_shardmap,
    "zero1_fused": _build_zero1_fused,
    "fsdp": _build_fsdp,
    "tp": _build_tp,
    "layout_dp_fsdp": _build_layout_dp_fsdp,
    "layout_fsdp_tp": _build_layout_fsdp_tp,
    "layout_dp_fsdp_tp": _build_layout_dp_fsdp_tp,
    "pp_1f1b": _build_pp_1f1b,
    "pp_planned": _build_pp_planned,
    "pp_zb": _build_pp_zb,
    "context": _build_context,
    "serve": _build_serve,
    "serve_paged": _build_serve_paged,
    "serve_paged_pallas": _build_serve_paged_pallas,
}


def variant_names() -> List[str]:
    return list(VARIANT_BUILDERS)


def build_variants(names: Optional[Sequence[str]] = None) -> List[StepVariant]:
    """Build the named variants (default: all).  Unknown names raise —
    a typo in a CI invocation must not silently skip a variant."""
    out: List[StepVariant] = []
    for n in (names or variant_names()):
        if n not in VARIANT_BUILDERS:
            raise ValueError(
                f"unknown variant {n!r}; registered: {variant_names()}")
        out.extend(VARIANT_BUILDERS[n]())
    return out
