#!/usr/bin/env python
"""Attention-core microbenchmark: Pallas flash vs XLA blockwise vs dense.

The framework's hand-written hot-op (ops/pallas_attention.py) exists to
beat the dense core's HBM behavior at long T; this measures whether it
does on real hardware — per-core ms and achieved TFLOP/s for forward and
forward+backward at growing sequence lengths, causal, bf16.

    python benchmarks/attention_bench.py                    # TPU
    python benchmarks/attention_bench.py --platform cpu \
        --seqlens 128 --batch 1 --heads 2 --dim 32          # smoke

Attention FLOPs ≈ 4·B·H·T²·D forward (q·kᵀ + p·v), halved when causal;
backward ≈ 2.5× forward.  Run under `timeout`, never kill a TPU client.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, _HERE)

# the shared pure-function timing protocol (3-step post-compile warmup),
# so attention rows are measured like every other hw_session row
from train_step_segments import timeit  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--seqlens", default="1024,2048,4096")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--block", type=int, default=128)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--window", type=int, default=None,
                    help="add a windowed pallas-flash row (block-skip "
                         "FLOPs saving at long T)")
    ap.add_argument("--decode", action="store_true",
                    help="run the flash-DECODE section instead: one "
                         "query row per slot vs the serve cache layouts "
                         "(dense cursor / windowed ring + sinks / paged "
                         "pool), pallas fast path vs the engine's XLA "
                         "gather+mask path")
    ap.add_argument("--slots", type=int, default=8,
                    help="decode section: concurrent slots (batch rows)")
    ap.add_argument("--max-len", type=int, default=2048,
                    help="decode section: reserved cache rows per slot")
    ap.add_argument("--live", type=int, default=128,
                    help="decode section: live tokens per slot (the "
                         "cursor position — the fast path's win scales "
                         "with max-len/live)")
    ap.add_argument("--kv-block-size", type=int, default=16,
                    help="decode section: paged pool rows per block")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from fluxdistributed_tpu.ops.attention import (
        blockwise_attention, dot_product_attention,
    )
    from fluxdistributed_tpu.ops.pallas_attention import flash_attention

    if args.decode:
        return decode_main(args, jax, jnp)

    B, H, D = args.batch, args.heads, args.dim
    blk = args.block
    cores = [
        ("dense", jax.jit(lambda q, k, v: dot_product_attention(q, k, v, causal=True))),
        ("blockwise-xla", jax.jit(
            lambda q, k, v: blockwise_attention(q, k, v, block_size=blk, causal=True))),
        ("pallas-flash", jax.jit(
            lambda q, k, v: flash_attention(q, k, v, True, blk, blk))),
    ]
    if args.window is not None:
        w = args.window
        if w < 1:
            raise SystemExit(f"--window must be >= 1, got {w}")
        cores.append((f"pallas-flash-w{w}", jax.jit(
            lambda q, k, v: flash_attention(q, k, v, True, blk, blk, w))))
    grads = {
        name: jax.jit(jax.grad(lambda q, k, v, f=fn: jnp.sum(f(q, k, v).astype(jnp.float32)),
                               argnums=(0, 1, 2)))
        for name, fn in cores
    }

    rows = []
    for t in [int(s) for s in args.seqlens.split(",")]:
        rng = np.random.default_rng(0)
        q, k, v = (
            jnp.asarray(rng.normal(0, 1, (B, t, H, D)), jnp.bfloat16)
            for _ in range(3)
        )
        fwd_flops = 4 * B * H * t * t * D / 2  # causal halves the score work
        if args.window is not None:
            # the windowed kernel's USEFUL work is the band, not T^2/2:
            # sum_q min(q+1, W) attended keys (otherwise its TFLOP/s
            # column would overstate by ~T/W and could exceed chip peak)
            w = min(args.window, t)
            attended = w * (w + 1) // 2 + max(t - w, 0) * w
            fwd_flops_windowed = 4 * B * H * D * attended
        for name, fn in cores:
            if name == "dense" and t > 8192:
                continue  # T^2 scores in HBM; keep the sweep bounded
            dt = timeit(fn, q, k, v, n=args.iters)
            dtg = timeit(grads[name], q, k, v, n=max(5, args.iters // 2))
            fl = fwd_flops_windowed if name.startswith("pallas-flash-w") else fwd_flops
            rows.append({
                "core": name, "T": t,
                "fwd_ms": round(dt * 1e3, 3),
                "fwd_tflops": round(fl / dt / 1e12, 2),
                "fwdbwd_ms": round(dtg * 1e3, 3),
            })
            print(json.dumps(rows[-1]), flush=True)

    print(json.dumps({
        "metric": "attention-core microbench (causal, bf16)",
        "config": {"B": B, "H": H, "D": D, "block": blk},
        "platform": jax.devices()[0].platform,
        "rows": rows,
    }))


def decode_main(args, jax, jnp):
    """Flash-decode vs the engine's XLA decode attention, op-level.

    One query row per slot against each serve cache layout, at a LIVE
    length far below the reserved rows — the regime continuous batching
    actually runs in.  The XLA side is exactly what the engine's model
    computes per step (full-cache mask; paged adds the pool gather);
    the pallas side is `ops.pallas_decode` under its default impl
    resolution (compiled kernel on TPU, the same block-walk schedule as
    an XLA fallback elsewhere — both skip dead blocks/pages, neither
    gathers dead cache).
    """
    import numpy as np

    from fluxdistributed_tpu.ops.attention import dot_product_attention
    from fluxdistributed_tpu.ops.pallas_decode import (
        flash_decode, flash_decode_paged, resolve_decode_impl,
    )

    B, H, D = args.slots, args.heads, args.dim
    R, live, bs = args.max_len, min(args.live, args.max_len), args.kv_block_size
    window, sinks = args.window or 256, 4
    rng = np.random.default_rng(0)
    dt = jnp.float32 if jax.devices()[0].platform == "cpu" else jnp.bfloat16

    def arr(*shape):
        return jnp.asarray(rng.normal(0, 1, shape), dt)

    q = arr(B, 1, H, D)
    idx = jnp.full((B,), live - 1, jnp.int32)
    rows = []

    def measure(name, xla_fn, pal_fn, operands, nbytes_live):
        # operands are ARGUMENTS (not closures): constants would let
        # XLA fold small cases away and time nothing
        tx = timeit(jax.jit(xla_fn), *operands, n=args.iters)
        tp = timeit(jax.jit(pal_fn), *operands, n=args.iters)
        rows.append({
            "layout": name,
            "xla_ms": round(tx * 1e3, 3),
            "pallas_ms": round(tp * 1e3, 3),
            "pallas_speedup_x": round(tx / tp, 2),
            "live_kv_bytes": int(nbytes_live),
        })
        print(json.dumps(rows[-1]), flush=True)

    # --- dense plain: cursor block-skip vs full-R mask --------------------
    k, v = arr(B, R, H, D), arr(B, R, H, D)

    def dense_xla(q, k, v, idx):
        allow = (jnp.arange(R)[None, :] <= idx[:, None])[:, None, None, :]
        return dot_product_attention(q, k, v, mask=allow)

    measure(
        f"dense R={R} live={live}",
        dense_xla,
        lambda q, k, v, idx: flash_decode(q, k, v, idx),
        (q, k, v, idx),
        2 * B * live * H * D * jnp.dtype(dt).itemsize,
    )

    # --- windowed ring + sinks (compact ring, slot_pos band mask) ---------
    ring_rows = sinks + window + bs
    kr, vr = arr(B, ring_rows, H, D), arr(B, ring_rows, H, D)
    sp0 = np.full((ring_rows,), -1, np.int32)
    ring = ring_rows - sinks
    cur = live - 1
    for p in range(live):  # the ring's write layout at cursor `cur`
        slot = p if p < sinks else sinks + (p - sinks) % ring
        if p < sinks or p > cur - ring:
            sp0[slot] = p
    sp = jnp.asarray(np.tile(sp0, (B, 1)))

    def ring_xla(q, kr, vr, sp, idx):
        qg = idx[:, None]
        allow = (sp >= 0) & (sp <= qg)
        allow &= (sp > qg - window) | (sp < sinks)
        return dot_product_attention(q, kr, vr, mask=allow[:, None, None, :])

    measure(
        f"ring window={window}+sinks={sinks}",
        ring_xla,
        lambda q, kr, vr, sp, idx: flash_decode(
            q, kr, vr, idx, slot_pos=sp, window=window, sinks=sinks),
        (q, kr, vr, sp, idx),
        2 * B * min(live, ring_rows) * H * D * jnp.dtype(dt).itemsize,
    )

    # --- paged pool: page-table walk vs gather + full mask ----------------
    pages = -(-R // bs)
    live_pages = -(-live // bs)
    nb = B * pages  # full-capacity pool
    kp, vp = arr(nb, bs, H, D), arr(nb, bs, H, D)
    pt = np.full((B, pages), -1, np.int32)
    for bb in range(B):  # live prefix bound, everything else unbound
        pt[bb, :live_pages] = bb * pages + np.arange(live_pages)
    pt = jnp.asarray(pt)

    def paged_xla(q, kp, vp, pt, idx):
        # the engine model's XLA path: gather the slot view, mask it
        gk = kp[jnp.maximum(pt, 0)].reshape(B, pages * bs, H, D)
        gv = vp[jnp.maximum(pt, 0)].reshape(B, pages * bs, H, D)
        allow = (jnp.arange(pages * bs)[None, :] <= idx[:, None])
        allow &= jnp.repeat(pt >= 0, bs, axis=1)
        return dot_product_attention(q, gk, gv, mask=allow[:, None, None, :])

    measure(
        f"paged R={R} bs={bs} live={live}",
        paged_xla,
        lambda q, kp, vp, pt, idx: flash_decode_paged(q, kp, vp, pt, idx),
        (q, kp, vp, pt, idx),
        2 * B * live_pages * bs * H * D * jnp.dtype(dt).itemsize,
    )

    best = max(rows, key=lambda r: r["pallas_speedup_x"])
    print(json.dumps({
        "metric": f"flash-decode vs XLA decode attention "
                  f"({jax.devices()[0].platform}, "
                  f"impl={resolve_decode_impl(None)}, B={B}, H={H}, D={D}, "
                  f"R={R}, live={live})",
        "value": best["pallas_speedup_x"],
        "unit": f"x faster than the XLA decode path (best: {best['layout']})",
        "rows": rows,
    }))


if __name__ == "__main__":
    main()
