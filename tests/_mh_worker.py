"""Worker script for the multi-process (fake multi-host) tests.

Launched as ``python tests/_mh_worker.py <process_id> <num_processes> <port>``
by tests/test_multihost.py.  Each process owns 2 virtual CPU devices; the
global mesh spans ``2 * num_processes`` devices across processes, with
gloo collectives standing in for ICI/DCN — the CPU fake-cluster analog of
the reference testing its sync machinery without GPUs
(test/single_device.jl:121-151; the reference's process mode itself has
NO tests, SURVEY §4).
"""

import sys

import numpy as np


def main() -> int:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from fluxdistributed_tpu.parallel import multihost

    multihost.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
        platform="cpu",
        local_devices=2,
    )

    import jax
    import jax.numpy as jnp

    assert jax.process_count() == nproc, jax.process_count()
    assert jax.device_count() == 2 * nproc, jax.device_count()

    from fluxdistributed_tpu import data_mesh, optim
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.models import SimpleCNN
    from fluxdistributed_tpu.train import prepare_training, train
    from fluxdistributed_tpu.train.logging import NullLogger

    mesh = data_mesh()

    # -- global_batch: per-process local rows -> one global sharded array
    local = np.arange(4, dtype=np.float32) + 100.0 * pid
    g = multihost.global_batch(local, mesh)
    assert g.shape == (4 * nproc,), g.shape
    total = jax.jit(
        jnp.sum,
        out_shardings=jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
    )(g)
    expect = sum(float(np.sum(np.arange(4) + 100.0 * p)) for p in range(nproc))
    assert float(total) == expect, (float(total), expect)

    # -- full DP training across processes: one compiled step, grads
    #    all-reduced over gloo (the RemoteChannel hub's replacement)
    ds = SyntheticDataset(nsamples=256, nclasses=10, shape=(16, 16, 3))
    task = prepare_training(
        SimpleCNN(num_classes=10),
        ds,
        optim.momentum(0.05, 0.9),
        mesh=mesh,
        batch_size=4 * nproc,
        cycles=3,
        val_dataset=ds,
        val_samples=8 * nproc,
    )
    train(task, print_every=0, eval_every=2, logger=NullLogger())
    assert int(task.state.step) == 3

    # replicated params must be identical across processes: compare a
    # param fingerprint via host allgather (ensure_synced analog,
    # src/ddp_tasks.jl:115-126)
    leaf = jax.tree.leaves(task.state.params)[0]
    fp = float(jnp.sum(jnp.abs(leaf)))
    fps = multihost.host_local_values(np.asarray([fp], np.float32))
    assert np.allclose(fps, fps[0]), fps

    # -- device loop across processes: the chunked loader's stacked
    #    [K, batch, ...] layout places per-process rows on dim 1 via
    #    global_batch_put(batch_dim=1) — the path only multi-process
    #    runs exercise — and the scanned step advances K steps/dispatch
    task_dl = prepare_training(
        SimpleCNN(num_classes=10),
        ds,
        optim.momentum(0.05, 0.9),
        mesh=mesh,
        batch_size=4 * nproc,
        cycles=4,
        steps_per_call=2,
    )
    item = next(iter(task_dl.loader))
    assert item["image"].shape == (2, 4 * nproc, 16, 16, 3), item["image"].shape
    train(task_dl, print_every=0, eval_every=0, logger=NullLogger())
    assert int(task_dl.state.step) == 4
    leaf = jax.tree.leaves(task_dl.state.params)[0]
    fp = float(jnp.sum(jnp.abs(leaf)))
    fps = multihost.host_local_values(np.asarray([fp], np.float32))
    assert np.allclose(fps, fps[0]), fps
    print(f"worker {pid}: device-loop OK", flush=True)

    # -- cooperative abort: any process voting stop stops everyone
    assert multihost.agree_to_stop(pid == 0) is True
    assert multihost.agree_to_stop(False) is False

    multihost.sync_global_devices("done")
    print(f"worker {pid}: OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
