#!/usr/bin/env python
"""End-to-end real-ingest training throughput: JPEG files on disk →
native decode → PrefetchLoader → compiled train step, as ONE system.

docs/benchmarks.md's "ingest outruns the step" margin claim multiplies a
single-core decode rate by an assumed host core count; this script
OBSERVES the full path instead (VERDICT r3 missing #1): it generates an
ILSVRC-layout tree of real JPEG files (the reference's actual workload —
bin/driver.jl:6-14 parses LOC_train_solution.csv from such a tree,
README.md:27-50), trains ResNet-50 fed by the threaded loader, and
reports achieved img/s against the same step fed device-resident
synthetic data.  Healthy = ingest-fed ≥ 90% of synthetic.

Usage (TPU host):  python benchmarks/ingest_e2e.py
Smoke (CPU):       python benchmarks/ingest_e2e.py --platform cpu \
                       --classes 4 --per-class 8 --batch 32 --size 64 --steps 8
Run under `timeout` and let it exit by itself (never kill a TPU client).
Prints a table plus one JSON line for regression tracking.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_ilsvrc_tree(root: str, classes: int, per_class: int, size=(500, 375)):
    """A miniature, real ILSVRC layout: synset mapping, train-solution
    CSV, and real JPEG files at the ILSVRC median size."""
    from PIL import Image

    rng = np.random.default_rng(0)
    wnids = [f"n{90000000 + c:08d}" for c in range(classes)]
    with open(os.path.join(root, "LOC_synset_mapping.txt"), "w") as f:
        for w in wnids:
            f.write(f"{w} synthetic class {w}\n")
    rows = ["ImageId,PredictionString"]
    for w in wnids:
        d = os.path.join(root, "ILSVRC", "Data", "CLS-LOC", "train", w)
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            image_id = f"{w}_{i}"
            base = rng.normal(0, 1, (8, 8, 3))
            arr = np.kron(base, np.ones((-(-size[1] // 8), -(-size[0] // 8), 1)))
            arr = ((arr - arr.min()) / (np.ptp(arr) + 1e-9) * 255).astype(np.uint8)
            arr = arr[: size[1], : size[0]]
            Image.fromarray(arr).save(os.path.join(d, image_id + ".JPEG"), quality=85)
            rows.append(f"{image_id},{w} 1 2 3 4")
    with open(os.path.join(root, "LOC_train_solution.csv"), "w") as f:
        f.write("\n".join(rows) + "\n")
    return wnids


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--per-class", type=int, default=64)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--threads", type=int, default=8,
                    help="decode threads inside the dataset")
    ap.add_argument("--loader-threads", type=int, default=2,
                    help="prefetch assembly threads in the loader")
    ap.add_argument("--root", default=None,
                    help="existing ILSVRC-layout tree (default: generate one)")
    ap.add_argument("--s2d", action="store_true",
                    help="space_to_depth model + host-side re-layout in the "
                         "loader transform (the full MXU-stem input path)")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import fluxdistributed_tpu as fd
    from fluxdistributed_tpu import optim, sharding
    from fluxdistributed_tpu.data import (
        ImageNetDataset, PrefetchLoader, labels, train_solutions,
    )
    from fluxdistributed_tpu.data.native import available as native_available
    from fluxdistributed_tpu.models import resnet50
    from fluxdistributed_tpu.parallel import TrainState, make_train_step
    from fluxdistributed_tpu.parallel.dp import flax_loss_fn

    tmp = None
    root = args.root
    if root is None:
        tmp = tempfile.TemporaryDirectory(prefix="ingest_e2e_")
        root = tmp.name
        t0 = time.perf_counter()
        make_ilsvrc_tree(root, args.classes, args.per_class)
        print(f"fixture: {args.classes * args.per_class} JPEGs in "
              f"{time.perf_counter() - t0:.1f}s  (native={native_available()})")

    lt = labels(os.path.join(root, "LOC_synset_mapping.txt"))
    table = train_solutions(os.path.join(root, "LOC_train_solution.csv"), lt)
    ds = ImageNetDataset(
        root, table, nclasses=len(lt), crop=args.size,
        resize=max(256 * args.size // 224, args.size + 8),
        num_threads=args.threads,
    )

    mesh = fd.data_mesh()
    model = resnet50(num_classes=len(lt), space_to_depth=args.s2d)
    rng = np.random.default_rng(0)
    x0 = rng.normal(0, 1, (args.batch, args.size, args.size, 3)).astype(np.float32)
    transform = None
    if args.s2d:
        from fluxdistributed_tpu.models import space_to_depth

        x0 = np.ascontiguousarray(space_to_depth(x0))

        def transform(imgs, labels):
            return np.ascontiguousarray(space_to_depth(imgs)), labels
    variables = model.init(jax.random.PRNGKey(0), x0[:1], train=True)
    params = variables["params"]
    mstate = {k: v for k, v in variables.items() if k != "params"}
    step = make_train_step(
        flax_loss_fn(model, fd.logitcrossentropy), optim.momentum(0.1, 0.9), mesh
    )
    state = TrainState.create(
        sharding.replicate(params, mesh), optim.momentum(0.1, 0.9),
        model_state=sharding.replicate(mstate, mesh),
    )

    # -- synthetic ceiling: device-resident batch, no ingest ------------
    b0 = sharding.shard_batch(
        {"image": x0, "label": np.asarray(fd.onehot(
            rng.integers(0, len(lt), args.batch), len(lt)))}, mesh
    )
    state, m = step(state, b0)
    jax.block_until_ready(m["loss"])  # compile
    for _ in range(3):  # bench.py's warm-up protocol
        state, m = step(state, b0)
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(max(3, args.steps // 3)):
        state, m = step(state, b0)
    jax.block_until_ready(m["loss"])
    dt_syn = (time.perf_counter() - t0) / max(3, args.steps // 3)
    syn_ips = args.batch / dt_syn
    print(f"synthetic-fed: {syn_ips:.0f} img/s  ({dt_syn * 1e3:.1f} ms/step)")

    # -- ingest-fed: disk → decode → prefetch → step --------------------
    # Consume buffersize+1 batches BEFORE timing: the prefetch buffer
    # fills while the step compiles/warms, and counting those pre-decoded
    # batches would inflate the timed rate by up to buffersize/steps
    buffersize = 5
    warm = buffersize + 1
    loader = PrefetchLoader(
        ds, mesh, args.batch, cycles=args.steps + warm,
        buffersize=buffersize, num_threads=args.loader_threads,
        transform=transform,
    )
    it = iter(loader)
    for _ in range(warm):
        state, m = step(state, next(it))
    jax.block_until_ready(m["loss"])  # steady state: decode vs step race is live
    t0 = time.perf_counter()
    n = 0
    for b in it:
        state, m = step(state, b)
        n += args.batch
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0
    ing_ips = n / dt
    ratio = ing_ips / syn_ips
    print(f"ingest-fed:    {ing_ips:.0f} img/s over {args.steps} steps "
          f"-> {ratio * 100:.0f}% of synthetic")

    out = {
        "metric": "ResNet-50 ingest-fed train throughput",
        "img_per_sec_ingest": round(ing_ips, 1),
        "img_per_sec_synthetic": round(syn_ips, 1),
        "ingest_over_synthetic": round(ratio, 3),
        "batch": args.batch,
        "decode_threads": args.threads,
        "loader_threads": args.loader_threads,
        "native": bool(native_available()),
        "s2d": bool(args.s2d),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out))
    if tmp is not None:
        tmp.cleanup()


if __name__ == "__main__":
    main()
