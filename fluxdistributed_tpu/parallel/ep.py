"""Expert parallelism (MoE): switch-style top-1 routing with capacity,
experts sharded one-per-device over an ``expert`` mesh axis.

Net-new scope beyond the reference (SURVEY §2: "EP: NO"), built the
TPU-classic way (Mesh-TF/Switch lineage): tokens are sharded over the
same ``expert`` axis, routing/dispatch build ``(tokens, experts,
capacity)`` one-hots locally, and two ``all_to_all`` collectives move
token activations to their expert's device and back — dense einsums and
static shapes throughout, so XLA keeps everything on the MXU (no
gather/scatter in the hot path).

Semantics (Switch Transformer):
* top-1 expert per token, output scaled by the router probability;
* per-shard expert capacity ``C = ceil(tokens_per_shard / E *
  capacity_factor)``; tokens over capacity are DROPPED (output zero) —
  the documented switch behavior;
* auxiliary load-balance loss ``E * Σ_e f_e · p_e`` (fraction routed ×
  mean router prob), returned for the caller to add to the task loss.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any

__all__ = ["moe_apply", "router_dispatch", "stack_expert_params"]

EXPERT_AXIS = "expert"


def stack_expert_params(per_expert: list, mesh: Mesh, axis: str = EXPERT_AXIS) -> Pytree:
    """Stack E per-expert param trees on a leading dim sharded over
    ``axis`` — expert e's params live on expert-device e."""
    from ..sharding import stack_on_axis

    return stack_on_axis(per_expert, mesh, axis)


def router_dispatch(logits: jnp.ndarray, capacity: int):
    """Top-1 dispatch/combine tensors from router logits.

    ``logits``: (T, E).  Returns ``dispatch`` (T, E, C) {0,1},
    ``combine`` (T, E, C) = dispatch · router prob, and the switch
    load-balance auxiliary loss.  Pure jnp — used identically inside the
    sharded program and by the single-device golden model in tests.
    """
    t, e = logits.shape
    dtype = logits.dtype
    # routing math in f32 regardless of compute dtype: a bf16 cumsum
    # saturates at 256, collapsing every later queue position onto slot
    # 255 (silent dispatch corruption for large expert queues)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # (T,)
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # (T, E)
    # position of each token in its expert's queue (0-based)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0
    kept = (pos >= 0) & (pos < capacity)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
    dispatch = (pos_oh * kept.astype(jnp.float32)[..., None]).astype(dtype)
    gate = jnp.max(probs * onehot, axis=-1)  # (T,) routed prob, f32
    combine = (dispatch.astype(jnp.float32) * gate[:, None, None]).astype(dtype)
    # load-balance aux: E * Σ_e (fraction of tokens to e) · (mean prob of e)
    frac = onehot.mean(axis=0)
    mean_p = probs.mean(axis=0)
    aux = e * jnp.sum(frac * mean_p)
    return dispatch, combine, aux


def moe_apply(
    expert_fn: Callable,
    mesh: Mesh,
    axis: str = EXPERT_AXIS,
    capacity_factor: float = 1.25,
    capacity: Optional[int] = None,
):
    """Build ``fn(stacked_params, router_w, x) -> (y, aux)``.

    ``x``: (T, D) tokens sharded on ``axis``; ``router_w``: (D, E)
    replicated; ``stacked_params`` leaves (E, ...) sharded on ``axis``.
    E must equal the ``axis`` size (one expert per device).  Output is
    token-sharded like ``x``; ``aux`` is the replicated (pmean-ed)
    load-balance loss.
    """
    e_devices = mesh.shape[axis]

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(), P(axis)),
        out_specs=(P(axis), P()),
    )
    def run(stacked_params, router_w, x):
        params = jax.tree.map(lambda p: p[0], stacked_params)  # my expert
        t, d = x.shape
        e = router_w.shape[-1]
        assert e == e_devices, f"experts ({e}) must equal '{axis}' size ({e_devices})"
        if capacity is not None:
            if capacity < 1:
                raise ValueError(f"capacity must be >= 1, got {capacity}")
            cap = capacity
        else:
            cap = max(1, math.ceil(t / e * capacity_factor))
        logits = x @ router_w
        dispatch, combine, aux = router_dispatch(logits, cap)
        # (T,D),(T,E,C) → (E,C,D): each expert's queue from this shard
        expert_in = jnp.einsum("td,tec->ecd", x, dispatch)
        # exchange: device e receives every shard's queue for expert e
        expert_in = jax.lax.all_to_all(
            expert_in, axis, split_axis=0, concat_axis=0, tiled=False
        )  # (S, C, D) with S = number of shards
        s = expert_in.shape[0]
        y = expert_fn(params, expert_in.reshape(s * cap, d)).reshape(s, cap, d)
        # route results back to the token-owning shards
        y = jax.lax.all_to_all(y, axis, split_axis=0, concat_axis=0, tiled=False)
        out = jnp.einsum("ecd,tec->td", y, combine)
        return out, jax.lax.pmean(aux, axis)

    return run
