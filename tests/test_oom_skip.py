"""OOM fault-tolerance integration tests.

The reference catches device OOM inside its task loop and skips the
batch (src/ddp_tasks.jl:230-238) with a ``num_missed`` counter that is
declared but never incremented (:178, :240).  Here the counter is live
and the two guard branches (donated state, multi-host) raise with clear
messages — these tests exercise all three paths by injecting a failing
step_fn, the analog of the reference's ``TaskFailedException`` wrapping.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from fluxdistributed_tpu import optim
from fluxdistributed_tpu.data import SyntheticDataset
from fluxdistributed_tpu.models import resnet18
from fluxdistributed_tpu.train import prepare_training, train
from fluxdistributed_tpu.train.logging import NullLogger


def _task(cycles=4, donate=False):
    ds = SyntheticDataset(nsamples=64, nclasses=10, shape=(16, 16, 3))
    return prepare_training(
        resnet18(num_classes=10, dtype=jnp.float32),
        ds,
        optim.momentum(0.1, 0.9),
        batch_size=16,
        cycles=cycles,
        donate=donate,
    )


class _FakeOOM(Exception):
    pass


def _inject_oom_once(task, msg="RESOURCE_EXHAUSTED: fake injected OOM"):
    real = task.step_fn
    calls = {"n": 0}

    def failing(state, batch):
        calls["n"] += 1
        if calls["n"] == 1:
            raise _FakeOOM(msg)
        return real(state, batch)

    task.step_fn = failing
    return calls


def test_oom_skips_batch_and_continues():
    task = _task(cycles=4)
    _inject_oom_once(task)
    train(task, print_every=0, eval_every=0, logger=NullLogger())
    assert task.num_missed == 1
    # 4 cycles, first skipped -> 3 applied steps
    assert int(task.state.step) == 3


def test_non_oom_errors_propagate():
    task = _task(cycles=2)
    _inject_oom_once(task, msg="INVALID_ARGUMENT: something else entirely")
    with pytest.raises(_FakeOOM):
        train(task, print_every=0, eval_every=0, logger=NullLogger())
    assert task.num_missed == 0


def test_oom_with_donated_state_raises():
    class _DeletedLeaf:
        def is_deleted(self):
            return True

    task = _task(cycles=2, donate=True)

    def failing(state, batch):
        # simulate: buffers were donated to the failed execution
        from fluxdistributed_tpu.parallel.dp import TrainState

        task.state = TrainState(
            params={"w": _DeletedLeaf()},
            opt_state=state.opt_state,
            model_state=state.model_state,
            step=state.step,
        )
        raise _FakeOOM("RESOURCE_EXHAUSTED: fake injected OOM")

    task.step_fn = failing
    with pytest.raises(RuntimeError, match="donate=True"):
        train(task, print_every=0, eval_every=0, logger=NullLogger())


# slow tier: secondary cursor/logging assertions on a second full
# trainer build; the core skip-and-continue behavior stays fast
@pytest.mark.slow
def test_oom_skip_advances_cursor_and_logs_global_index():
    """The skipped-step path advances the data cursor and records the
    skipped batch's global index — the bookkeeping resume-after-skip
    parity depends on."""
    logged = []

    class Capture(NullLogger):
        def log(self, metrics, step=None):
            logged.append((dict(metrics), step))

    task = _task(cycles=4)
    _inject_oom_once(task)
    train(task, print_every=0, eval_every=0, logger=Capture())
    assert task.skipped_items == [0]
    assert any(m.get("oom_skipped_item") == 0 for m, _ in logged)
    # cursor advanced past the skip: 4 items consumed, 3 steps applied
    assert int(task.state.step) == 3


def _mlp_task(cycles=5):
    """A cheap task for the resume-parity flow (three prepares; an MLP
    compiles in a fraction of resnet18's time)."""
    from fluxdistributed_tpu.data import SyntheticDataset as DS
    from fluxdistributed_tpu.models import MLP

    ds = DS(nsamples=64, nclasses=10, shape=(8, 8, 3))
    return prepare_training(
        MLP(features=(10, 10)), ds, optim.adam(1e-3),
        batch_size=8, cycles=cycles, topk=())


def test_oom_skip_then_preempt_resume_replays_deterministically(tmp_path):
    """Resume after an OOM-skip: the manifest's cursor counts the
    skipped item, so the resumed run replays the exact remaining
    stream — losses match an uninterrupted run with the same skip."""
    from fluxdistributed_tpu import faults
    from fluxdistributed_tpu.train import read_resume_manifest, resume_training

    def record(task):
        losses = []
        orig = task.step_fn

        def wrapped(state, batch):
            out = orig(state, batch)
            losses.append(float(out[1]["loss"]))
            return out

        task.step_fn = wrapped
        return losses

    # baseline: item 0 OOM-skipped, run to completion
    ta = _mlp_task(cycles=5)
    _inject_oom_once(ta)
    la = record(ta)
    train(ta, print_every=0, eval_every=0, logger=NullLogger())
    assert len(la) == 4  # items 1..4

    # same skip, preempted at item 2, resumed
    tb = _mlp_task(cycles=5)
    _inject_oom_once(tb)
    lb = record(tb)
    faults.install_plan(faults.FaultPlan().sigterm_at_step(2))
    try:
        with pytest.raises(faults.Preempted):
            train(tb, print_every=0, eval_every=0, logger=NullLogger(),
                  checkpoint_dir=str(tmp_path), checkpoint_every=0,
                  handle_signals=True)
    finally:
        faults.clear_plan()
    m = read_resume_manifest(tmp_path)
    assert m["next_item"] == 2          # cursor counts the skipped item
    assert m["checkpoint_step"] == 1    # only item 1 actually stepped
    assert m["num_missed"] == 1
    assert m["skipped_items"] == [0]

    tb2 = _mlp_task(cycles=5)
    lb2 = record(tb2)
    resume_training(tb2, str(tmp_path))
    assert tb2.num_missed == 1 and tb2.skipped_items == [0]
    train(tb2, print_every=0, eval_every=0, logger=NullLogger())
    assert lb + lb2 == la
    assert int(tb2.state.step) == 4


def test_oom_multihost_raises(monkeypatch):
    from fluxdistributed_tpu.parallel import multihost

    task = _task(cycles=2)
    _inject_oom_once(task)
    # Fake a 2-process world for the trainer's guard; keep the loader's
    # batch assembly single-process (it would otherwise try to stitch a
    # half-batch from each "process").
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost, "global_batch_put", jax.device_put)
    with pytest.raises(RuntimeError, match="multi-host"):
        train(task, print_every=0, eval_every=0, logger=NullLogger())
