"""Bytes sources — local, HTTP, and GCS dataset backends with caching.

The reference's ``Data.toml`` registers datasets on two storage drivers:
a local ``FileSystem`` tree and a remote S3-backed ``JuliaHubDataRepo``
(Data.toml:4-27); DataSets.jl hides the difference behind a BlobTree.
The TPU-native analog (pods read from GCS in practice): a *source*
object mapping dataset-relative paths to bytes, with remote sources
caching fetched files locally so the hot path (native JPEG decode, which
wants real file paths) is always a local read.

* ``FileSource``  — a plain directory tree.
* ``HTTPSource``  — ``http(s)://`` base URL + local cache.
* ``GCSSource``   — ``gs://bucket/prefix`` via the public GCS HTTP
  endpoint (``storage.googleapis.com``) — no cloud SDK dependency; for
  private buckets set ``GCS_OAUTH_TOKEN`` (sent as a Bearer header).

``make_source`` dispatches on the scheme, so every ``path`` in the
dataset registry (data/registry.py) may be a local dir or a remote URL.
"""

from __future__ import annotations

import os
import tempfile
import time
import urllib.error
import urllib.parse
import urllib.request

__all__ = ["FileSource", "HTTPSource", "GCSSource", "make_source"]


class FileSource:
    """Local directory tree (the reference's FileSystem driver,
    Data.toml:4-12)."""

    is_local = True

    def __init__(self, root: str):
        self.root = root

    @property
    def location(self) -> str:
        """User-facing dataset location (directory or URL)."""
        return self.root

    def local_path(self, rel: str) -> str:
        """Path of ``rel`` on the local filesystem (no copy)."""
        return os.path.join(self.root, rel)

    def open_bytes(self, rel: str) -> bytes:
        with open(self.local_path(rel), "rb") as f:
            return f.read()

    def __repr__(self):
        return f"FileSource({self.root!r})"


class HTTPSource:
    """Remote tree behind a base URL, cached under ``cache_dir``.

    ``local_path`` fetches on first access (atomic rename, so concurrent
    decode threads never see partial files) and serves the cache
    afterwards — the local-cache semantics DataSets.jl gives the
    reference's S3 dataset.
    """

    is_local = False

    def __init__(self, base_url: str, cache_dir: str | None = None, headers=None):
        self.base_url = base_url.rstrip("/")
        # Always namespace the cache by base URL — two datasets sharing a
        # cache_dir must never serve each other's files (identical
        # relative paths like LOC_synset_mapping.txt would collide).
        key = urllib.parse.quote(self.base_url, safe="")
        if cache_dir is None:
            cache_dir = os.environ.get(
                "FDTPU_CACHE", os.path.expanduser("~/.cache/fdtpu")
            )
        self.cache_dir = os.path.join(cache_dir, key)
        self.headers = dict(headers or {})

    def _request_headers(self) -> dict:
        return self.headers

    @property
    def location(self) -> str:
        return self.base_url

    def _url(self, rel: str) -> str:
        return f"{self.base_url}/{urllib.parse.quote(rel)}"

    #: request timeout (s) and transient-status retry schedule — object
    #: storage at pod request rates throws occasional 429/5xx and expects
    #: exponential backoff; a stalled connection must not wedge a decode
    #: worker forever.
    timeout = 30.0
    retry_backoff = (1.0, 2.0, 4.0)

    def open_bytes(self, rel: str) -> bytes:
        last: Exception | None = None
        for i in range(len(self.retry_backoff) + 1):
            req = urllib.request.Request(
                self._url(rel), headers=self._request_headers()
            )
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    return r.read()
            except urllib.error.HTTPError as e:
                if e.code not in (429, 500, 502, 503, 504):
                    raise
                last = e
            except (urllib.error.URLError, TimeoutError, OSError) as e:
                last = e
            if i < len(self.retry_backoff):
                time.sleep(self.retry_backoff[i])
        raise last  # type: ignore[misc]

    def local_path(self, rel: str) -> str:
        dest = os.path.join(self.cache_dir, rel)
        if os.path.exists(dest):
            return dest
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        data = self.open_bytes(rel)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dest), suffix=".part")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, dest)  # atomic: concurrent fetchers race benignly
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        return dest

    def __repr__(self):
        return f"{type(self).__name__}({self.base_url!r}, cache={self.cache_dir!r})"


class GCSSource(HTTPSource):
    """``gs://bucket/prefix`` via the public GCS JSON/XML HTTP endpoint."""

    def __init__(self, gs_url: str, cache_dir: str | None = None):
        parsed = urllib.parse.urlparse(gs_url)
        if parsed.scheme != "gs" or not parsed.netloc:
            raise ValueError(f"not a gs:// URL: {gs_url!r}")
        base = f"https://storage.googleapis.com/{parsed.netloc}{parsed.path}"
        super().__init__(base, cache_dir=cache_dir)
        self.gs_url = gs_url

    @property
    def location(self) -> str:
        return self.gs_url

    def _request_headers(self) -> dict:
        # Re-read per request: OAuth tokens expire (~1h), and first-epoch
        # fetch phases on large datasets run far longer than that — a
        # refresher process can rotate GCS_OAUTH_TOKEN mid-run.
        headers = dict(self.headers)
        token = os.environ.get("GCS_OAUTH_TOKEN")
        if token:
            headers["Authorization"] = f"Bearer {token}"
        return headers


def make_source(path_or_url: str, cache_dir: str | None = None):
    """Dispatch a registry ``path`` to the right source by scheme."""
    scheme = urllib.parse.urlparse(str(path_or_url)).scheme
    if scheme == "gs":
        return GCSSource(path_or_url, cache_dir=cache_dir)
    if scheme in ("http", "https"):
        return HTTPSource(path_or_url, cache_dir=cache_dir)
    return FileSource(path_or_url)
