from .cifar import CIFAR10Dataset
from .imagenet import ImageNetDataset, SampleTable, labels, makepaths, train_solutions
from .loader import PrefetchLoader
from .preprocess import preprocess
from .registry import load_registry, open_dataset, register_dataset
from .sources import FileSource, GCSSource, HTTPSource, make_source
from .synthetic import SyntheticDataset, SyntheticTextDataset
from .text import ByteTextDataset

__all__ = [
    "CIFAR10Dataset",
    "ImageNetDataset",
    "SampleTable",
    "labels",
    "makepaths",
    "train_solutions",
    "PrefetchLoader",
    "preprocess",
    "load_registry",
    "open_dataset",
    "register_dataset",
    "FileSource",
    "HTTPSource",
    "GCSSource",
    "make_source",
    "SyntheticDataset",
    "SyntheticTextDataset",
    "ByteTextDataset",
    "minibatch",
]


def minibatch(dataset, n: int, rng=None, one_hot: bool = True):
    """Sample one host-side minibatch — the exported ``minibatch`` analog
    (reference src/imagenet.jl:23-48, exported at src/FluxDistributed.jl:11).

    With-replacement sampling; returns ``(images [n,H,W,C] f32,
    labels)`` with labels one-hot (``Flux.onehotbatch`` analog) unless
    ``one_hot=False``.
    """
    import numpy as np

    from ..ops import onehot

    if rng is None:
        rng = np.random.default_rng()
    imgs, y = dataset.batch(rng, n)
    if one_hot:
        y = np.asarray(onehot(y, dataset.nclasses))
    return imgs, y
