"""ConvNeXt family: forward contract, DP training, LARS config.

The BASELINE 'ConvNeXt-XL / ImageNet-21k large-batch (LARS)' config is
exercised end-to-end at test scale: ConvNeXt blocks + LARS optimizer on
the 8-fake-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# tier-2 (slow): ConvNeXt compiles + torch parity — the tier-1 iteration loop must fit the
# 870s verify window (ROADMAP); CI's slow job still runs this file
pytestmark = pytest.mark.slow

from fluxdistributed_tpu import mesh as mesh_lib
from fluxdistributed_tpu import optim, sharding
from fluxdistributed_tpu.models import (
    convnext_test,
    convnext_tiny,
    convnext_xlarge,
)
from fluxdistributed_tpu.ops import logitcrossentropy, onehot
from fluxdistributed_tpu.parallel import TrainState, make_train_step
from fluxdistributed_tpu.parallel.dp import flax_loss_fn


@pytest.fixture(scope="module")
def mesh():
    return mesh_lib.data_mesh(8)


def test_forward_shape_and_dtype():
    model = convnext_test(num_classes=10)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10) and out.dtype == jnp.float32


def test_param_counts_scale_with_config():
    from fluxdistributed_tpu import tree as tree_lib

    x = jnp.zeros((1, 32, 32, 3), jnp.float32)
    n_tiny = tree_lib.count_params(
        convnext_tiny().init(jax.random.PRNGKey(0), x, train=False)["params"]
    )
    # published ConvNeXt-T is ~28.6M params
    assert 27e6 < n_tiny < 30e6


def test_xlarge_config_shapes():
    m = convnext_xlarge()
    assert m.dims == (256, 512, 1024, 2048) and m.depths == (3, 3, 27, 3)


def test_drop_path_stochastic_in_train_deterministic_in_eval():
    # layer_scale_init=1 so dropped branches change the output measurably
    model = convnext_test(num_classes=10, drop_path_rate=0.5, layer_scale_init=1.0)
    x = jnp.ones((4, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    e1 = model.apply(variables, x, train=False)
    e2 = model.apply(variables, x, train=False)
    np.testing.assert_array_equal(e1, e2)  # eval: no stochastic depth
    t1 = model.apply(variables, x, train=True, rngs={"dropout": jax.random.PRNGKey(1)})
    t2 = model.apply(variables, x, train=True, rngs={"dropout": jax.random.PRNGKey(2)})
    assert not np.allclose(t1, t2)  # different keys drop different branches


def test_drop_path_trains_through_the_trainer(mesh):
    """Stochastic depth must work through prepare_training/train (the
    step makers thread a per-step 'dropout' rng into the model)."""
    from fluxdistributed_tpu import optim as optim_lib
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.train import prepare_training, train
    from fluxdistributed_tpu.train.logging import NullLogger

    ds = SyntheticDataset(nsamples=32, nclasses=4, shape=(32, 32, 3))
    for spmd in ("jit", "shard_map"):
        task = prepare_training(
            convnext_test(num_classes=4, drop_path_rate=0.3, layer_scale_init=1.0),
            ds, optim_lib.momentum(0.05, 0.9),
            mesh=mesh, batch_size=16, cycles=2, spmd=spmd,
        )
        train(task, print_every=0, eval_every=0, logger=NullLogger())
        assert int(task.state.step) == 2


def test_dp_training_with_lars_loss_falls(mesh):
    """The BASELINE ConvNeXt+LARS config at test scale: loss must fall on
    a separable task under the compiled DP step."""
    model = convnext_test(num_classes=2)
    rng = np.random.default_rng(0)
    n = 32
    y = rng.integers(0, 2, n)
    x = rng.normal(0, 0.3, (n, 32, 32, 3)).astype(np.float32) + y[:, None, None, None]

    variables = model.init(jax.random.PRNGKey(0), x[:1], train=True)
    loss_fn = flax_loss_fn(model, logitcrossentropy)
    opt = optim.lars(0.5, momentum_coef=0.9, trust_coefficient=0.01)
    step = make_train_step(loss_fn, opt, mesh)
    state = TrainState.create(sharding.replicate(variables["params"], mesh), opt)
    batch = sharding.shard_batch(
        {"image": x, "label": np.asarray(onehot(y, 2))}, mesh
    )
    losses = []
    for _ in range(30):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.5, losses[::10]
