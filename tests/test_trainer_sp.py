"""Sequence/context parallelism as a trainer mode.

``spmd="sp"`` rides the plain jit path with replicated params; the
model's mesh-bound context-parallel attention (ring or Ulysses — the
driver's ``--sp-strategy`` flag) shards the sequence dimension over the
``seq`` axis inside its own shard_map while the batch stays
data-sharded.  The trainer's job is mesh validation — everything else
is the standard surface.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu import mesh as mesh_lib, optim
from fluxdistributed_tpu.data import SyntheticTextDataset
from fluxdistributed_tpu.models import lm_loss_fn
from fluxdistributed_tpu.models.transformer_lm import TransformerLM
from fluxdistributed_tpu.parallel import (
    make_ring_attention,
    make_ulysses_attention,
)
from fluxdistributed_tpu.train import prepare_training

VOCAB = 32

_STRATEGIES = {"ring": make_ring_attention, "ulysses": make_ulysses_attention}


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_sp_trainer_mode_trains(tmp_path, strategy):
    mesh = mesh_lib.make_mesh({"data": 2, "seq": 4})
    # Ulysses re-shards heads over the seq axis: 4 heads / seq=4.
    model = TransformerLM(
        vocab=VOCAB, dim=32, depth=2, num_heads=4, mlp_dim=64,
        dtype=jnp.float32, dropout=0.0,
        attn_fn=_STRATEGIES[strategy](mesh, batch_axis="data", causal=True),
    )
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=32, peak=0.95)
    task = prepare_training(
        model, ds, optim.adam(3e-3),
        mesh=mesh, batch_size=16, cycles=30, spmd="sp",
        loss_fn=lm_loss_fn(model), topk=(),
        val_dataset=ds, val_samples=8,
    )
    losses = []
    for batch in task.loader:
        task.state, m = task.step_fn(task.state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    loss, _ = task.eval_fn(task.state, task.val_batch)
    assert np.isfinite(float(loss))


def test_sp_mode_rejects_missing_seq_axis():
    model = TransformerLM(
        vocab=VOCAB, dim=32, depth=2, num_heads=2, mlp_dim=64,
        dtype=jnp.float32, dropout=0.0,
    )
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=32)
    with pytest.raises(ValueError, match="seq"):
        prepare_training(
            model, ds, optim.adam(1e-3),
            mesh=mesh_lib.data_mesh(8), batch_size=16, spmd="sp",
            loss_fn=lm_loss_fn(model), topk=(),
        )


def _driver_env():
    """Child env for bin/driver.py subprocesses: package importable from
    the repo root, parent's fake-device pin scrubbed (--local-devices
    sets its own)."""
    import os

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.mark.slow
def test_driver_cli_ulysses_one_flag(tmp_path):
    """--spmd sp --sp-strategy ulysses is a one-flag trainer mode:
    lm_tiny (4 heads) over a {data: 2, seq: 4} mesh, end to end."""
    import os
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, os.path.join("bin", "driver.py"),
         "--model", "lm_tiny", "--dataset", "synthetic-text",
         "--vocab", "32", "--seqlen", "32", "--batch-size", "8",
         "--cycles", "2", "--opt", "adam", "--lr", "1e-3",
         "--print-every", "1", "--eval-every", "0",
         "--spmd", "sp", "--sp-strategy", "ulysses", "--seq-parallel", "4",
         "--platform", "cpu", "--local-devices", "8"],
        capture_output=True, text=True, timeout=600, env=_driver_env(),
    )
    assert out.returncode == 0, out.stderr[-3000:]
    assert "done: 2 steps" in out.stdout, out.stdout[-2000:]


@pytest.mark.slow
def test_driver_cli_ulysses_head_divisibility_guard():
    """lm_small has 12 heads: a seq axis of 8 must be rejected up front
    with an actionable message, not a trace-time assert."""
    import os
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, os.path.join("bin", "driver.py"),
         "--model", "lm_small", "--dataset", "synthetic-text",
         "--seqlen", "64", "--batch-size", "8", "--cycles", "1",
         "--spmd", "sp", "--sp-strategy", "ulysses",
         "--platform", "cpu", "--local-devices", "8"],
        capture_output=True, text=True, timeout=300, env=_driver_env(),
    )
    assert out.returncode != 0
    assert "divisible by the seq axis" in out.stderr, out.stderr[-2000:]


def test_unknown_spmd_rejected():
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=32)
    model = TransformerLM(
        vocab=VOCAB, dim=32, depth=2, num_heads=2, mlp_dim=64,
        dtype=jnp.float32, dropout=0.0,
    )
    with pytest.raises(ValueError, match="unknown spmd"):
        prepare_training(
            model, ds, optim.adam(1e-3),
            mesh=mesh_lib.data_mesh(8), batch_size=16, spmd="typo",
            loss_fn=lm_loss_fn(model), topk=(),
        )
