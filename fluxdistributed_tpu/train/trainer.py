"""Training orchestration — ``prepare_training`` + ``train``.

TPU-native re-design of the reference's orchestration layer
(src/ddp_tasks.jl:174-289).  Where the reference spawns one Julia task
per GPU, hub-reduces gradients on a HOST device and applies N replicated
optimizer steps, here ``prepare_training`` compiles ONE SPMD train step
over the mesh and ``train`` is a plain Python loop around it.  Feature
parity points, with their reference anchors:

* epoch→cycle accounting and per-shard loaders with prefetch
  (``prepare_training`` src/ddp_tasks.jl:249-289) → ``PrefetchLoader``;
* cycle print every 10 / eval every 50 with top-{1,5,10} accuracy on a
  val slice AND the current train batch
  (``train`` :185-191, ``log_loss_and_acc`` :128-148) → same cadences,
  configurable;
* LR-schedule callback kwarg (``sched`` :174,193-195 — unused identity
  in the reference) → schedules compile into the step via
  ``optim`` schedules; a per-cycle ``sched`` callback is still accepted
  and its value logged for parity;
* OOM fault tolerance: the reference catches device OOM and skips the
  batch with a (dead) ``num_missed`` counter (:230-238; counter declared
  :178, never incremented) → here the counter is live and returned;
* final host-side model return (:241-246) → ``train`` returns host
  copies of params/state.
"""

from __future__ import annotations

import contextlib
import copy
import dataclasses
import sys
import time
from typing import Any, Callable, Iterable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .. import mesh as mesh_lib
from .. import sharding as sharding_lib
from .. import tree as tree_lib
from ..data.loader import PrefetchLoader
from ..obs import Observation, jaxmon
from ..ops import logitcrossentropy
from ..optim import Optimizer
from ..parallel.dp import TrainState, flax_loss_fn, make_eval_step, make_train_step
from .guard import state_donated
from .logging import Logger, current_logger

__all__ = ["TrainTask", "evaluate", "prepare_training", "train"]


@dataclasses.dataclass
class TrainTask:
    """Everything ``train`` needs — the analog of the reference's
    ``(ds_and_ms, dls, sts), buffer`` bundle returned by
    ``prepare_training`` (src/ddp_tasks.jl:288), collapsed into one
    compiled step + one replicated state."""

    state: TrainState
    step_fn: Callable
    eval_fn: Callable
    loader: Iterable
    optimizer: Optimizer
    mesh: Mesh
    model: Any
    val_batch: Optional[dict] = None
    num_missed: int = 0
    # host-side batch hook (the loader's ``transform``), kept so
    # ``evaluate`` feeds the model the same layout training did
    transform: Optional[Callable] = None
    # optimizer steps per dispatch (the device loop); loader items carry
    # this many stacked batches and metrics come back stacked
    steps_per_call: int = 1
    # every global batch fed to step_fn/eval_fn must be a multiple of
    # this (0 = just the data-axis size).  Pipeline modes set it to
    # data_size x num_microbatches: the compiled schedule reshapes each
    # data shard into M microbatches, so eval/val batches must divide
    batch_quantum: int = 0
    # loader-item indices skipped by OOM fault tolerance, in order —
    # recorded so a resumed run can prove the cursor accounting (the
    # RESUME manifest carries them) and postmortems can name the lost
    # batches by global index
    skipped_items: list = dataclasses.field(default_factory=list)
    # loader-item indices quarantined by the anomaly guard (train/guard
    # .py) — restored from the RESUME manifest by resume_training so a
    # resumed/rolled-back run deterministically re-skips the same
    # batches (the loss-parity contract extends to guard decisions)
    quarantined_items: list = dataclasses.field(default_factory=list)
    # the top-k metrics compiled into eval_fn; ``train`` reports these
    # by default so a mode that compiles loss-only eval (the LM
    # pipelines) needs no caller-side coordination
    topk: tuple = (1, 5, 10)
    # the mesh axes the batch dim shards over — one name for the
    # classic modes, ("data", "fsdp") for the rule-derived 3-D layouts
    # (evaluate/shard paths must split batches over BOTH communicators)
    batch_axes: Any = mesh_lib.DATA_AXIS


def _eval_view(dataset):
    """A non-mutating eval view of ``dataset``: same tables/decoders,
    augmentation off.

    Eval draws must go through the eval pipeline even when the dataset
    augments its train split — but toggling ``dataset.augment`` in place
    (the old scheme) races a concurrent prefetch loader sharing the
    object, which would silently draw un-augmented TRAIN batches while
    an eval runs.  A shallow copy gives the eval path its own ``augment``
    flag while sharing the (read-only) sample tables underneath.
    """
    if getattr(dataset, "augment", False):
        view = copy.copy(dataset)
        view.augment = False
        return view
    return dataset


def prepare_training(
    model,
    dataset,
    optimizer: Optimizer,
    *,
    mesh: Optional[Mesh] = None,
    batch_size: int = 32,
    epochs: int = 1,
    cycles: Optional[int] = None,
    loss: Callable = logitcrossentropy,
    loss_fn: Optional[Callable] = None,
    val_dataset=None,
    val_samples: int = 300,
    buffersize: int = 5,
    seed: int = 0,
    input_shape: Optional[Sequence[int]] = None,
    spmd: str = "jit",
    zero1: bool = False,
    layout=None,
    donate: bool = False,
    topk: Sequence[int] = (1, 5, 10),
    accum_steps: int = 1,
    transform: Optional[Callable] = None,
    steps_per_call: int = 1,
    num_microbatches: Optional[int] = None,
    pipeline_interleave: bool = False,
    pipeline_schedule: str = "1f1b",
    pp_plan=None,
    cache_dir: Optional[str] = None,
    aot: Optional[str] = None,
    warmup: bool = False,
    strict_checks: bool = False,
    guard: bool = False,
) -> TrainTask:
    """Initialize params, compile the SPMD step, build prefetch loaders.

    Mirrors ``prepare_training(model, key, devices, opt, nsamples; ...)``
    (src/ddp_tasks.jl:249-289) with the device list replaced by a mesh and
    the per-device replication/buffers replaced by sharding annotations.

    ``val_samples`` defaults to the reference's 300-sample val slice
    (src/ddp_tasks.jl:145).  ``spmd`` selects the compiled path: ``"jit"``
    (auto-sharded DP; ``"dp"`` is an alias), ``"shard_map"`` (explicit
    collectives), or ``"fsdp"`` (ZeRO-3: params + optimizer state sharded
    across the data axis, see ``parallel/fsdp.py`` — same step math, ~N×
    lower state memory on an N-way mesh).

    ``zero1=True`` upgrades the DP paths (``"jit"``/``"dp"``/
    ``"shard_map"``) to ZeRO-1 weight-update sharding
    (``parallel/zero1.py``): gradients reduce-scatter, the optimizer
    state and update compute shard 1/N over the data axis, updated
    params all-gather — DP-identical numerics at ~N× lower optimizer
    memory.  Composes with ``accum_steps``, ``steps_per_call``,
    ``donate`` and OOM-skip; checkpoints carry the sharded optimizer
    state (orbax restores shard-to-shard).

    ``donate=True`` donates the TrainState buffers to each step (halves
    peak state memory — worthwhile for very large models) but is
    incompatible with OOM-skip: after a failed step the donated buffers
    are gone and training cannot continue (the loop raises a clear error
    instead of continuing).  Default False, matching the reference's
    skip-and-continue semantics (src/ddp_tasks.jl:230-238).

    ``loss_fn`` overrides the default image-classification adapter
    (``flax_loss_fn(model, loss)``) with any function matching the
    framework loss signature — e.g. ``models.lm_loss_fn(model)`` trains
    the transformer LM on a token dataset through this same path (pass
    ``topk=()``: top-k image metrics don't apply to LM batches).

    ``transform`` is the loader's host-side batch hook (per the dataset
    protocol: ``transform(imgs, labels)`` for tuple datasets, one
    argument otherwise) — e.g. ``models.space_to_depth`` re-layout for a
    ``space_to_depth=True`` ResNet.  It is applied consistently to the
    init sample, the train loader, the val slice, and ``evaluate``.

    ``steps_per_call > 1`` turns on the device loop: each loader item
    stacks K per-step batches and the compiled program ``lax.scan``s K
    optimizer steps per dispatch — identical math and identical sampled
    data (sub-batch j of item c equals step c·K+j of an unchunked run),
    but the host pays one dispatch per K steps.  Worthwhile when the
    runtime sits behind a network tunnel or the host is slow; cadences
    in ``train`` (print/eval/checkpoint) then tick once per K steps.
    Supported for ``spmd='jit'``.

    Pipeline knobs (``spmd="pp"``/``"pp_1f1b"``): ``num_microbatches``
    sets M (default 2·S), ``pipeline_interleave`` the Megatron
    round-robin virtual stages, ``pipeline_schedule="zb"`` the
    zero-bubble B/W-split timetable (pp_1f1b only; bit-identical
    gradients, W work fills the drain), and ``pp_plan`` a
    :class:`~..parallel.pp_plan.PipelinePlan` (or saved-plan path)
    whose profile-guided non-uniform stage boundaries replace the
    uniform block split — cross-topology plans are rejected through
    the profile fingerprint check, and a plan lifts the
    ``depth % S == 0`` requirement.

    Cold-start controls (:mod:`fluxdistributed_tpu.compilation`):

    * ``cache_dir`` enables JAX's persistent compilation cache there
      (namespaced per topology) BEFORE any compile in this call, so the
      next process on the same topology reads every XLA compile from
      disk instead of redoing it.
    * ``aot`` names a directory of serialized train-step executables:
      the compiled step is loaded from disk when a file matching this
      topology + argument signature exists, else compiled NOW (at
      prepare time, not at first step) and serialized for the next
      process.  Unlike the persistent cache, a serialized executable
      also skips tracing and lowering.  Requires a jit-compiled step
      (every current spmd mode qualifies).
    * ``warmup=True`` runs one optimizer step on donated zero-filled
      dummies (the returned task's real state is untouched) before
      returning, so the first ``train`` step — and anything timing it —
      starts warm.

    ``guard=True`` compiles the anomaly sentinel into the train step
    (``parallel.dp.guard_sentinel``: ``metrics["guard"] =
    [poisoned_loss, grad_norm]``, the global isfinite any-reduce over
    loss + grads plus the global grad norm, in-graph where the
    gradients already live) so ``train(guard=GuardConfig(...))`` can
    detect bad steps at ONE extra scalar fetch per step and zero extra
    compiles.  Supported on the paths that ride
    ``dp.make_train_step`` — ``jit``/``dp`` (with or without
    ``zero1``), ``sp``, ``ep`` and the GPipe ``pp`` — and requires
    ``donate=False``: recovery re-uses the pre-step state, exactly like
    OOM-skip.  Other modes still run the guard loss-only (non-finite
    loss + spikes) without this flag.

    ``strict_checks=True`` arms the returned step/eval functions for
    their first TWO invocations: call 1 runs with ``jax_debug_nans`` on
    (a NaN/Inf in the outputs raises and jax re-runs op-by-op to name
    the producing primitive), call 2 under
    ``jax.transfer_guard("disallow")`` (any implicit host↔device
    transfer raises — the hazard the lint suite's FDT205 check hunts;
    the guard sits on the steady-state call because step-0 one-time
    commits are legitimate).  Failures raise with an actionable message
    naming the offending phase ("first train step" / "steady-state
    eval step"); subsequent calls run at full speed with both checks
    off.  Debug-grade: the armed calls also block until the device
    finishes.
    """
    from ..data.loader import apply_transform

    if cache_dir:
        from .. import compilation

        compilation.enable_persistent_cache(cache_dir)

    if spmd == "dp":  # explicit-name alias for the auto-sharded DP path
        spmd = "jit"
    if layout is not None:
        # the declarative path (parallel/rules.py + parallel/layout.py):
        # a dp×fsdp×tp Layout (or preset name) whose rule-derived spec
        # tree drives the UNCHANGED dp step — it subsumes the modes it
        # composes, so combining it with one of them is a contradiction
        from ..parallel import layout as layout_lib

        if spmd != "jit":
            raise ValueError(
                f"layout= builds the rule-derived 3-D step and cannot "
                f"combine with spmd={spmd!r} (keep the default "
                "spmd='jit'/'dp')")
        if zero1:
            raise ValueError(
                "layout= cannot combine with zero1=True: a layout's "
                "fsdp axis already shards the optimizer state "
                "(ZeRO-3 placement subsumes ZeRO-1) — use e.g. "
                "layout='fsdp' or 'dp_fsdp'")
        if steps_per_call != 1:
            raise ValueError("steps_per_call > 1 is not supported with "
                             "layout= (yet) — drop one of them")
        # a caller-supplied mesh defines the topology (it may span a
        # device SUBSET — build_mesh(devs=...) is supported surface);
        # validate_mesh below still pins the axis sizes exactly
        layout = layout_lib.resolve_layout(
            layout,
            ndev=int(mesh.devices.size) if mesh is not None else None)
    if steps_per_call < 1:
        raise ValueError(f"steps_per_call must be >= 1, got {steps_per_call}")
    if steps_per_call != 1 and spmd != "jit":
        raise ValueError("steps_per_call > 1 requires spmd='jit'")
    if zero1 and spmd not in ("jit", "shard_map"):
        raise ValueError(
            "zero1=True applies to the DP paths only (spmd='jit'/'dp'/"
            f"'shard_map'); got spmd={spmd!r} — fsdp already shards the "
            "optimizer state (ZeRO-3 subsumes ZeRO-1)"
        )
    if guard:
        if donate:
            raise ValueError(
                "guard=True requires donate=False: anomaly recovery "
                "discards the poisoned step and continues from the "
                "PRE-step state, which donation would have freed "
                "(the same contract as OOM-skip)")
        if spmd not in ("jit", "sp", "ep", "pp"):
            raise ValueError(
                f"guard=True compiles the grad sentinel into "
                f"dp.make_train_step, which spmd={spmd!r} does not use "
                "(supported: jit/dp [+zero1], sp, ep, pp) — the guard "
                "still runs loss-only there: drop guard=True and pass "
                "train(guard=GuardConfig(...))")
    if num_microbatches is not None and spmd not in ("pp", "pp_1f1b"):
        raise ValueError("num_microbatches requires spmd='pp' or 'pp_1f1b'")
    if num_microbatches is not None and num_microbatches < 1:
        # validated HERE with the other argument checks, before any
        # pipeline-specific model wiring, so the error fires identically
        # across spmd modes and model types
        raise ValueError(
            f"num_microbatches must be >= 1, got {num_microbatches}")
    if pipeline_interleave and spmd != "pp_1f1b":
        raise ValueError(
            "pipeline_interleave requires spmd='pp_1f1b' (the hand-written "
            "schedule; GPipe-via-AD cannot interleave)")
    if pipeline_schedule not in ("1f1b", "zb"):
        raise ValueError(
            f"unknown pipeline_schedule {pipeline_schedule!r} "
            "(pick '1f1b' or 'zb')")
    if pipeline_schedule != "1f1b" and spmd != "pp_1f1b":
        raise ValueError(
            "pipeline_schedule='zb' requires spmd='pp_1f1b' (the zero-"
            "bubble B/W split only exists in the hand-written schedule)")
    if pp_plan is not None and spmd not in ("pp", "pp_1f1b"):
        raise ValueError("pp_plan requires spmd='pp' or 'pp_1f1b'")
    if pp_plan is not None and pipeline_interleave:
        raise ValueError(
            "pp_plan cannot combine with pipeline_interleave: planner "
            "boundaries are contiguous block ranges, the interleaved "
            "placement is round-robin")
    if layout is not None:
        mesh = mesh or layout.build_mesh()
        layout.validate_mesh(mesh)
    else:
        mesh = mesh or mesh_lib.data_mesh()
    init_draw = None
    # a data-axis-divisible init sample for the modes whose models
    # contain a mesh-bound shard_map (ring attention, MoE dispatch) —
    # those execute it during init, and a batch of 1 cannot shard over
    # a >1 data axis.  Other modes keep the cheap single-sample init.
    ninit = mesh.shape.get(mesh_lib.DATA_AXIS, 1) if spmd in ("sp", "ep") else 1
    if input_shape is not None:
        dummy = np.zeros((ninit, *input_shape), np.float32)
    else:
        # draw real samples so init sees the dataset's true shape AND
        # dtype (f32 images, int32 tokens, ...); kept for the pp_1f1b
        # mask probe below so startup draws only once
        from ..data.loader import model_input

        init_draw = apply_transform(
            transform, dataset.batch(np.random.default_rng(0), ninit))
        dummy = model_input(init_draw)

    p_rng, d_rng = jax.random.split(jax.random.PRNGKey(seed))
    # 'dropout' stream present at init so stochastic models (ViT dropout,
    # ConvNeXt drop-path) initialize under train=True
    variables = model.init({"params": p_rng, "dropout": d_rng}, dummy, train=True)
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}  # e.g. batch_stats

    custom_loss_fn = loss_fn is not None
    if loss_fn is None:
        loss_fn = flax_loss_fn(model, loss)
    batch_quantum = 0  # pipeline modes raise it to data_size x microbatches
    batch_axes = mesh_lib.DATA_AXIS  # layouts widen it to (data, fsdp)
    if layout is not None:
        # declarative rule-derived sharding (ROADMAP item 3): the model
        # family's committed rule table + the fsdp overlay produce the
        # spec tree; the step itself is the UNCHANGED dp step compiled
        # with those shardings and the batch split over (data, fsdp) —
        # GSPMD derives the dp/ZeRO-3/Megatron collective composition
        # from the annotations, same as the hand-built fsdp/tp variants
        from ..parallel import layout as layout_lib
        from ..sharding import make_shardings, unaliased

        state = TrainState.create(params, optimizer, model_state=model_state)
        spec_state = layout_lib.state_specs_for(model, state, layout, mesh)
        sh = make_shardings(spec_state, mesh)

        def _put(x, s):
            return None if x is None else jax.device_put(unaliased(x), s)

        state = jax.tree.map(_put, state, sh, is_leaf=lambda x: x is None)
        batch_axes = layout.batch_axes
        if batch_size % layout.batch_shards:
            raise ValueError(
                f"batch_size {batch_size} must be divisible by the "
                f"layout's dp x fsdp = {layout.batch_shards} "
                f"({layout.describe()})")
        batch_quantum = layout.batch_shards
        step_fn = make_train_step(
            loss_fn, optimizer, mesh, axis=batch_axes,
            donate=donate, accum_steps=accum_steps, seed=seed,
            state_shardings=sh, guard=guard)
        eval_fn = make_eval_step(
            loss_fn, mesh, axis=batch_axes, topk=tuple(topk),
            state_shardings=sh)
    elif spmd in ("tp", "fsdp_tp"):
        # Megatron tensor parallelism over a (data, model) mesh; sharding
        # rules picked by model family ("fsdp_tp" additionally
        # FSDP-shards each large leaf's leftover dim over the data axis —
        # the hybrid 2-D recipe).  No rng stream threads through the TP
        # step — fine for the default dropout=0 configs.
        from ..models.transformer_lm import TransformerLM
        from ..models.vit import ViT
        from ..parallel.tp import (
            lm_tp_rules, make_train_step_tp, param_specs, shard_state,
            state_specs, vit_tp_rules,
        )
        from ..sharding import make_shardings

        if accum_steps != 1:
            raise ValueError("accum_steps > 1 requires spmd='jit' or 'fsdp'")
        if mesh_lib.MODEL_AXIS not in mesh.shape:
            raise ValueError(
                f"spmd={spmd!r} needs a mesh with a 'model' axis, e.g. "
                "make_mesh({'data': D, 'model': K})"
            )
        if getattr(model, "dropout", 0.0):
            raise ValueError(
                f"spmd={spmd!r} supports dropout=0 only (no rng stream "
                "threads through the TP step)"
            )
        if isinstance(model, ViT):
            rules = vit_tp_rules()
        elif isinstance(model, TransformerLM):
            rules = lm_tp_rules()
        else:
            raise ValueError(
                f"no TP sharding rules for {type(model).__name__}; "
                f"spmd={spmd!r} supports ViT and TransformerLM (CNN params "
                "are small — use DP/FSDP there)"
            )
        if spmd == "fsdp_tp":
            from ..parallel.fsdp import hybrid_fsdp_tp_specs

            specs = hybrid_fsdp_tp_specs(params, mesh, rules)
        else:
            specs = param_specs(params, rules)
        state = TrainState.create(params, optimizer, model_state=model_state)
        state = shard_state(state, mesh, specs)
        step_fn = make_train_step_tp(
            loss_fn, optimizer, mesh, specs, state, donate=donate
        )
        eval_fn = make_eval_step(
            loss_fn, mesh, topk=tuple(topk),
            state_shardings=make_shardings(state_specs(state, specs), mesh),
        )
    elif spmd in ("pp", "pp_1f1b"):
        # Pipeline-parallel LM training as a first-class trainer mode:
        # decoder blocks stage-sharded over a 'pipe' axis, composed with
        # data parallelism over the 'data' axis (size 1 is fine — build
        # the mesh as make_mesh({"data": D, "pipe": S})).  "pp" rides
        # the GPipe schedule through the generic jit step; "pp_1f1b"
        # compiles the hand-scheduled 1F1B train step (O(S) activation
        # memory) and still evaluates through the GPipe forward — both
        # schedules share the same split param tree and shardings.
        from ..models.transformer_lm import TransformerLM, lm_pp, lm_pp_1f1b
        from ..parallel.pp_1f1b import make_train_step_1f1b

        if not isinstance(model, TransformerLM):
            raise ValueError(
                f"spmd={spmd!r} supports TransformerLM only (CNN stages "
                "change activation shapes mid-network)"
            )
        if accum_steps != 1:
            raise ValueError("accum_steps > 1 requires spmd='jit' or 'fsdp'")
        if custom_loss_fn:
            raise ValueError(
                f"spmd={spmd!r} trains on the pipeline's own per-microbatch "
                "next-token loss; a loss_fn override cannot apply (drop it)"
            )
        # top-k image metrics can never apply to the LM pipeline; the
        # compiled eval returns loss only
        topk = ()
        for ax in (mesh_lib.PIPE_AXIS, mesh_lib.DATA_AXIS):
            if ax not in mesh.shape:
                raise ValueError(
                    f"spmd={spmd!r} needs a mesh with 'data' and 'pipe' "
                    "axes, e.g. make_mesh({'data': 1, 'pipe': 8})"
                )
        if model_state:
            raise ValueError(
                f"spmd={spmd!r} supports stateless models only "
                f"(got model_state collections {list(model_state)})"
            )
        if spmd == "pp_1f1b":
            # the 1F1B step's per-microbatch loss reads tokens only; a
            # mask-carrying dataset would train unmasked while eval (the
            # GPipe forward) applies the mask — reject the divergence
            from ..data.loader import batch_to_dict

            draw = init_draw if init_draw is not None else apply_transform(
                transform, dataset.batch(np.random.default_rng(0), 1))
            probe = batch_to_dict(draw, getattr(dataset, "nclasses", None))
            if "mask" in probe:
                raise ValueError(
                    "spmd='pp_1f1b' does not support batch['mask'] (the "
                    "1F1B per-microbatch loss reads tokens only) — use "
                    "spmd='pp', whose loss applies the mask"
                )
        S = mesh.shape[mesh_lib.PIPE_AXIS]
        n_data = mesh.shape[mesh_lib.DATA_AXIS]
        M = num_microbatches or 2 * S
        # planner boundaries: accept a PipelinePlan or a saved plan
        # artifact path; reject cross-topology plans (profile-derived
        # fingerprints) and plans for a different stack/axis
        boundaries = None
        if pp_plan is not None:
            from ..parallel.pp_plan import PipelinePlan

            if isinstance(pp_plan, str):
                pp_plan = PipelinePlan.load(pp_plan)
            pp_plan.verify_source_topology()
            if pp_plan.S != S:
                raise ValueError(
                    f"pp_plan places {pp_plan.S} stages but the "
                    f"'{mesh_lib.PIPE_AXIS}' axis has {S} — re-plan for "
                    "this mesh")
            if pp_plan.depth != model.depth:
                raise ValueError(
                    f"pp_plan partitions {pp_plan.depth} blocks but the "
                    f"model has depth {model.depth} — re-plan for this "
                    "model")
            boundaries = pp_plan.boundaries
        per_row = batch_size // n_data
        if batch_size % n_data or per_row % M:
            raise ValueError(
                f"batch_size {batch_size} must split into data axis "
                f"{n_data} x microbatches {M} (per-row batch {per_row})"
            )
        batch_quantum = n_data * M

        if pipeline_interleave:
            # interleaved placement's round-robin param layout cannot
            # feed the (blocked) GPipe forward, so BOTH the train step
            # and eval ride the 1F1B program (eval returns its loss and
            # discards the grads — ~3x a forward, fine for val slices)
            from ..parallel.pp_1f1b import pipeline_grads_1f1b
            from jax.sharding import NamedSharding, PartitionSpec as P

            w = lm_pp_1f1b(model, mesh, interleave=True)
            state = TrainState.create(w.split_params(params), optimizer)
            sh = w.state_shardings(state)
            state = jax.tree.map(jax.device_put, state, sh)
            step_fn = make_train_step_1f1b(
                *w.fns, optimizer, mesh, num_microbatches=M,
                batch_axis=mesh_lib.DATA_AXIS, interleave=w.interleave,
                donate=donate, schedule=pipeline_schedule,
            )(state)
            eval_run = pipeline_grads_1f1b(
                *w.fns, mesh, num_microbatches=M,
                batch_axis=mesh_lib.DATA_AXIS, interleave=w.interleave,
            )

            def _eval(state, batch):
                loss, _, _ = eval_run(
                    state.params["stages"], state.params["outer"],
                    batch["tokens"], batch["tokens"],
                )
                return loss, {}

            eval_fn = jax.jit(
                _eval,
                in_shardings=(sh, NamedSharding(mesh, P(mesh_lib.DATA_AXIS))),
            )
        else:
            split_params, pp_loss_fn, shardings_fn = lm_pp(
                model, mesh, batch_axis=mesh_lib.DATA_AXIS,
                num_microbatches=M, boundaries=boundaries,
            )
            state = TrainState.create(split_params(params), optimizer)
            sh = shardings_fn(state)
            state = jax.tree.map(jax.device_put, state, sh)
            if spmd == "pp":
                step_fn = make_train_step(
                    pp_loss_fn, optimizer, mesh, axis=mesh_lib.DATA_AXIS,
                    donate=donate, state_shardings=sh, guard=guard,
                )
            else:
                w = lm_pp_1f1b(model, mesh, boundaries=boundaries)
                step_fn = make_train_step_1f1b(
                    *w.fns, optimizer, mesh, num_microbatches=M,
                    batch_axis=mesh_lib.DATA_AXIS, interleave=w.interleave,
                    donate=donate, schedule=pipeline_schedule,
                )(state)
            # eval through the GPipe forward: same tree, same shardings
            eval_fn = make_eval_step(
                pp_loss_fn, mesh, topk=tuple(topk), state_shardings=sh
            )
    elif spmd == "ep":
        # MoE expert parallelism as a trainer mode: expert-stacked
        # leaves shard over the 'expert' axis, tokens ride the 'data'
        # axis, and the model's mesh-bound moe_fn (moe_apply) does the
        # all_to_all dispatch inside the generic jit step.  The model
        # must have been CONSTRUCTED with that moe_fn — it closes over
        # the mesh (bin/driver.py builds it from --spmd ep flags).
        from ..models.transformer_lm import TransformerLM, lm_loss_fn, lm_moe_specs
        from ..parallel.tp import state_specs
        from ..sharding import make_shardings

        if not isinstance(model, TransformerLM) or not model.moe_every:
            raise ValueError(
                "spmd='ep' needs a TransformerLM with moe_every > 0 and a "
                "mesh-bound moe_fn (models.moe_expert_fn via ep.moe_apply)"
            )
        if accum_steps != 1:
            raise ValueError("accum_steps > 1 requires spmd='jit' or 'fsdp'")
        for ax in (mesh_lib.EXPERT_AXIS, mesh_lib.DATA_AXIS):
            if ax not in mesh.shape:
                raise ValueError(
                    "spmd='ep' needs a mesh with 'data' and 'expert' axes, "
                    "e.g. make_mesh({'data': 1, 'expert': 8})"
                )
        if not custom_loss_fn:
            loss_fn = lm_loss_fn(model)  # token protocol, not image loss
        topk = ()  # image metrics can never apply to the LM
        state = TrainState.create(params, optimizer, model_state=model_state)
        sh = make_shardings(state_specs(state, lm_moe_specs(params)), mesh)
        state = jax.tree.map(jax.device_put, state, sh)
        step_fn = make_train_step(
            loss_fn, optimizer, mesh, axis=mesh_lib.DATA_AXIS,
            donate=donate, seed=seed, state_shardings=sh, guard=guard,
        )
        eval_fn = make_eval_step(loss_fn, mesh, topk=(), state_shardings=sh)
    elif spmd == "fsdp":
        from ..parallel import fsdp as fsdp_lib

        state = TrainState.create(params, optimizer, model_state=model_state)
        specs = fsdp_lib.fsdp_specs(state, mesh)
        state = fsdp_lib.shard_state(state, specs, mesh)
        step_fn = fsdp_lib.make_train_step_fsdp(
            loss_fn, optimizer, mesh, specs,
            donate=donate, accum_steps=accum_steps, seed=seed,
        )
        eval_fn = fsdp_lib.make_eval_step_fsdp(loss_fn, mesh, specs, topk=tuple(topk))
    else:
        if spmd not in ("jit", "shard_map", "sp"):
            raise ValueError(
                f"unknown spmd mode {spmd!r}; pick one of jit (alias dp) / "
                "shard_map / fsdp / tp / fsdp_tp / pp / pp_1f1b / ep / sp"
            )
        if spmd == "sp":
            # sequence/context parallelism rides the plain jit path with
            # REPLICATED params: the model's mesh-bound attn_fn (ring /
            # Ulysses, parallel/context.py) shards the sequence dim over
            # the 'seq' axis inside its own shard_map, and the batch
            # stays data-sharded.  Only the mesh shape needs checking.
            for ax in (mesh_lib.SEQ_AXIS, mesh_lib.DATA_AXIS):
                if ax not in mesh.shape:
                    raise ValueError(
                        "spmd='sp' needs a mesh with 'data' and 'seq' axes, "
                        "e.g. make_mesh({'data': 1, 'seq': 8}), and a model "
                        "built with attn_fn=make_ring_attention(mesh, "
                        "batch_axis='data', ...)"
                    )
        if spmd == "shard_map" and accum_steps != 1:
            raise ValueError("accum_steps > 1 requires spmd='jit'")
        if zero1:
            # ZeRO-1: DP step math, optimizer state + update sharded 1/N
            # over the data axis (parallel/zero1.py)
            from ..parallel import zero1 as zero1_lib

            state, z_sh = zero1_lib.zero1_state(
                params, optimizer, mesh, model_state=model_state
            )
            if spmd == "shard_map":
                step_fn = zero1_lib.make_train_step_zero1_shardmap(
                    loss_fn, optimizer, mesh, state, donate=donate, seed=seed
                )
            else:
                step_fn = zero1_lib.make_train_step_zero1(
                    loss_fn, optimizer, mesh, z_sh,
                    donate=donate, accum_steps=accum_steps, seed=seed,
                    steps_per_call=steps_per_call, guard=guard,
                )
            eval_fn = make_eval_step(
                loss_fn, mesh, topk=tuple(topk), state_shardings=z_sh
            )
        else:
            if spmd == "shard_map":
                from ..parallel.dp import make_train_step_shardmap as maker

                step_fn = maker(loss_fn, optimizer, mesh, donate=donate, seed=seed)
            else:
                step_fn = make_train_step(
                    loss_fn, optimizer, mesh,
                    donate=donate, accum_steps=accum_steps, seed=seed,
                    steps_per_call=steps_per_call, guard=guard,
                )
            eval_fn = make_eval_step(loss_fn, mesh, topk=tuple(topk))

            state = TrainState.create(
                sharding_lib.replicate(params, mesh),
                optimizer,
                model_state=sharding_lib.replicate(model_state, mesh),
            )

    loader = PrefetchLoader(
        dataset,
        mesh,
        batch_size,
        cycles=cycles,
        epochs=epochs,
        buffersize=buffersize,
        seed=seed,
        axis=batch_axes,
        transform=transform,
        chunk=steps_per_call,
    )

    val_batch = None
    if val_dataset is not None:
        # divisible val slice: a data-axis multiple, and for pipeline
        # modes a multiple of data_size x microbatches (the compiled
        # eval reshapes each data shard into M microbatches)
        q = batch_quantum or mesh.shape[mesh_lib.DATA_AXIS]
        nval = max(q, (val_samples // q) * q)
        # Validation must go through the eval pipeline even when the val
        # dataset was carved from an augmenting train table.
        vdraw = apply_transform(
            transform,
            _eval_view(val_dataset).batch(np.random.default_rng(seed + 1), nval),
        )
        from ..data.loader import batch_to_dict

        val_batch = sharding_lib.shard_batch(
            batch_to_dict(vdraw, getattr(val_dataset, "nclasses", None)),
            mesh, axis=batch_axes,
        )

    task = TrainTask(
        state=state,
        step_fn=step_fn,
        eval_fn=eval_fn,
        loader=loader,
        optimizer=optimizer,
        mesh=mesh,
        model=model,
        val_batch=val_batch,
        transform=transform,
        steps_per_call=steps_per_call,
        batch_quantum=batch_quantum,
        topk=tuple(topk),
        batch_axes=batch_axes,
    )

    if aot or warmup:
        from .. import compilation

        dummy = _dummy_batch(
            dataset, transform, batch_size, mesh, steps_per_call, seed,
            axis=batch_axes)
        if aot:
            # the tag covers everything that changes the compiled
            # program WITHOUT changing argument shapes: mode/schedule
            # knobs, model hyperparameters like attention windows, and
            # the optimizer/loss with their closed-over hyperparameters
            # (a different learning rate bakes different constants into
            # the same-shaped program — config_tag digests callables by
            # name + closure constants, address-free).  Argument
            # shapes/shardings are the signature's job inside
            # load_or_compile
            # "guard" appended only when on: the sentinel adds outputs
            # to the compiled program, so a guarded step must never
            # load an unguarded executable (or vice versa) — while
            # guard-off runs keep their pre-existing tags byte-for-byte
            # pipeline_schedule and the plan's boundaries both change
            # the compiled program at identical argument shapes (zb
            # adds W ticks + the cot stash; a plan re-pads the chunk
            # scan), so they must split the AOT key — appended only
            # when NON-default, so every pre-existing run keeps its
            # tag byte-for-byte (same contract as the guard flag: a
            # warm executable pool must survive this upgrade)
            tag = compilation.config_tag(
                spmd, zero1, accum_steps, steps_per_call, donate, seed,
                num_microbatches, pipeline_interleave, repr(model),
                optimizer.name, optimizer.update, loss_fn, loss,
                *(("guard",) if guard else ()),
                # a layout changes the compiled program (shardings) at
                # identical shapes; appended only when set so every
                # pre-existing run keeps its tag byte-for-byte
                *((f"layout:{layout.name}:{sorted(layout.sizes.items())}",)
                  if layout is not None else ()),
                *((pipeline_schedule,) if pipeline_schedule != "1f1b"
                  else ()),
                # a UNIFORM plan builds the no-plan program exactly, so
                # it must also share the no-plan AOT key
                *((repr(pp_plan.boundaries),)
                  if pp_plan is not None and not pp_plan.is_uniform
                  else ()))
            task.step_fn = compilation.load_or_compile(
                task.step_fn, (task.state, dummy),
                directory=aot, name="train_step",
                fingerprint=compilation.topology_fingerprint(
                    mesh=mesh, tag=tag),
            )
            # an AOT executable (unlike jit) does NOT reshard inputs:
            # commit the state to the exact shardings it was compiled
            # with (no-op transfers for already-matching leaves; the
            # step's output shardings keep the loop consistent after)
            in_sh = getattr(task.step_fn, "input_shardings", None)
            if in_sh is not None:
                task.state = jax.tree.map(
                    jax.device_put, task.state, in_sh[0][0])
        if warmup:
            stats = compilation.warmup_train(task, dummy)
            current_logger().info(
                f"warmup: {int(stats['compiles'])} compiles "
                f"({stats['compile_seconds']:.1f}s of "
                f"{stats['seconds']:.1f}s) pre-paid before step 0")

    if strict_checks:
        # a handful of state leaves (the step counter; any scalar the
        # optimizer creates from literals) are born on one device and
        # legitimately commit to their replicated sharding at the first
        # call — do that HERE so the transfer-guarded call only trips on
        # transfers that would recur every step
        task.state = _commit_replicated_stragglers(task.state, mesh)
        task.step_fn = _strict_first_call(task.step_fn, "train step")
        task.eval_fn = _strict_first_call(task.eval_fn, "eval step")

    return task


def _commit_replicated_stragglers(state, mesh: Mesh):
    """Commit any single-device state leaf to the replicated sharding on
    ``mesh``.  Mode-specific prepare paths device_put their whole state;
    the plain DP paths leave computation-born scalars (``state.step``)
    uncommitted, and ``strict_checks`` must not report the one-time
    step-0 commit of those as a hot-path transfer."""
    from jax.sharding import NamedSharding, PartitionSpec, SingleDeviceSharding

    if mesh.size <= 1:
        return state
    repl = NamedSharding(mesh, PartitionSpec())

    def fix(x):
        if isinstance(x, jax.Array) and isinstance(x.sharding, SingleDeviceSharding):
            return jax.device_put(x, repl)
        return x

    return jax.tree.map(fix, state)


def _strict_first_call(fn, phase: str):
    """``strict_checks`` wrapper: call 1 runs under ``jax_debug_nans``
    (a NaN/Inf raises and jax re-runs op-by-op to name the producing
    primitive), call 2 under ``jax.transfer_guard("disallow")`` (any
    implicit host↔device transfer raises); later calls pass straight
    through.  The two checks must not share a call: debug-nans' op-by-op
    re-run itself performs host transfers, so a guard around it would
    mask the NaN diagnosis with a transfer error.  Putting the guard on
    call 2 is also the honest check — step-0 one-time commits are
    legitimate, a transfer on call 2 recurs every step (same protocol as
    the lint suite's FDT205).  (The wrapper hides a jit object's
    ``.lower`` — AOT-export a task before arming it with strict
    checks.)"""
    stage = {"n": 0}

    def wrapped(*args, **kwargs):
        n = stage["n"]
        if n >= 2:
            return fn(*args, **kwargs)
        stage["n"] = n + 1
        if n == 0:
            old_nans = bool(jax.config.jax_debug_nans)
            jax.config.update("jax_debug_nans", True)
            try:
                out = fn(*args, **kwargs)
                # surface device-side NaN checks inside the debug
                # window, not at some later sync point
                jax.block_until_ready(jax.tree.leaves(out))
            except FloatingPointError as e:
                raise FloatingPointError(
                    f"strict_checks: NaN/Inf produced by the first "
                    f"{phase} — jax_debug_nans re-ran it op-by-op above "
                    "to name the producing primitive; check the input "
                    "batch, init scales and the learning rate"
                ) from e
            finally:
                jax.config.update("jax_debug_nans", old_nans)
            return out
        try:
            with jax.transfer_guard("disallow"):
                out = fn(*args, **kwargs)
                jax.block_until_ready(jax.tree.leaves(out))
        except Exception as e:
            msg = str(e)
            if "transfer" in msg.lower():
                raise RuntimeError(
                    f"strict_checks: implicit host<->device transfer "
                    f"during the steady-state {phase}: {msg[:300]} — "
                    "commit inputs up front (sharding.shard_batch for "
                    "batches, jax.device_put for state); a transfer here "
                    "recurs on EVERY step and serializes the dispatch "
                    "pipeline"
                ) from e
            raise
        return out

    return wrapped


def _dummy_batch(dataset, transform, batch_size, mesh, steps_per_call, seed,
                 axis=mesh_lib.DATA_AXIS):
    """One batch with training's exact layout (transform applied,
    device-sharded, stacked when the device loop is on) for AOT
    lowering and warmup — drawn from the dataset so shapes AND dtypes
    are the real ones, discarded after use."""
    from ..data.loader import apply_transform, batch_to_dict

    draw = apply_transform(
        transform, dataset.batch(np.random.default_rng(seed + 2), batch_size))
    bd = batch_to_dict(draw, getattr(dataset, "nclasses", None))
    if steps_per_call > 1:
        # the loader's chunk layout: K stacked per-step batches sharded
        # P(None, data) — leading dim is the scan axis, not the batch.
        # Routed through the canonical local-rows→global-array boundary
        # (batch_dim=1, like the loader) so multi-process warmup works
        from jax.sharding import NamedSharding, PartitionSpec

        from ..parallel.multihost import global_batch_put, local_batch_size

        s = NamedSharding(mesh, PartitionSpec(None, mesh_lib.DATA_AXIS))
        pi = jax.process_index()

        def put(v):
            rows = local_batch_size(v.shape[0])
            local = np.asarray(v[pi * rows:(pi + 1) * rows])
            return global_batch_put(
                np.stack([local] * steps_per_call), s, batch_dim=1)

        return {k: put(v) for k, v in bd.items()}
    return sharding_lib.shard_batch(bd, mesh, axis=axis)


def restore_training(
    task: TrainTask, checkpoint_dir: str, step: Optional[int] = None
) -> TrainTask:
    """Resume a prepared task from a checkpoint — the path the reference
    lacks entirely (SURVEY §5: "no resume"; its checkpoints hold model
    weights only, src/sync.jl:156-161, while ours carry params +
    optimizer state + BatchNorm stats + step counter).

    Restores the latest (or given) step from ``checkpoint_dir`` onto the
    task's mesh, replicated, ready for ``train``.  For preemption-aware
    resume (data-loader cursor + elastic device-count change) use
    :func:`resume_training`.
    """
    from .checkpoint import load_checkpoint

    task.state = load_checkpoint(checkpoint_dir, task.state, step=step, mesh=task.mesh)
    return task


def resume_training(
    task: TrainTask, checkpoint_dir: str, step: Optional[int] = None
) -> Optional[dict]:
    """Preemption-aware resume: restore state AND the run cursor so a
    resumed run is step-for-step identical to an uninterrupted one.

    Reads the RESUME manifest a preempted ``train`` left next to its
    checkpoint (step, data-loader cursor, skipped items, mesh
    topology).  When the manifest's topology matches the task's, the
    checkpoint restores sharded in place; on a device-count change
    (the elastic case — the next grant gave a different slice) it
    restores via host arrays and re-commits every leaf to the NEW
    mesh's shardings, re-splitting ZeRO-1's padded flat optimizer
    shards (:func:`..train.checkpoint.load_checkpoint_elastic`).

    Returns the manifest (or ``None``: no manifest — plain
    latest-checkpoint resume with the cursor derived from the step
    counter; or nothing on disk at all — the task is left untouched,
    a fresh run).
    """
    from .. import faults
    from .checkpoint import (
        latest_step, load_checkpoint, load_checkpoint_elastic,
        read_resume_manifest,
    )

    faults.fire("resume")
    manifest = read_resume_manifest(checkpoint_dir)
    ckpt_step = (manifest or {}).get("checkpoint_step", step)
    if ckpt_step is None:
        ckpt_step = latest_step(checkpoint_dir)
        if ckpt_step is None:
            return None  # nothing saved yet: fresh run
    mesh_now = {k: int(v) for k, v in dict(task.mesh.shape).items()}
    same_topology = manifest is None or (
        manifest.get("device_count") == jax.device_count()
        and manifest.get("mesh") == mesh_now
    )
    if same_topology:
        task.state = load_checkpoint(
            checkpoint_dir, task.state, step=ckpt_step, mesh=task.mesh)
    else:
        task.state = load_checkpoint_elastic(
            checkpoint_dir, task.state, step=ckpt_step)
    spc = max(1, getattr(task, "steps_per_call", 1))
    if manifest is not None:
        task.loader.start = int(manifest.get("next_item", 0))
        task.num_missed = int(manifest.get("num_missed", 0))
        task.skipped_items = list(manifest.get("skipped_items", []))
        # guard decisions survive the process: a resumed run re-skips
        # the quarantined batches (train() seeds its TrainGuard here)
        task.quarantined_items = [
            int(x) for x in manifest.get("quarantined_items", [])]
    else:
        # no manifest (a cadence checkpoint from an old-style run):
        # the step counter is the only cursor — correct when nothing
        # was OOM-skipped before the checkpoint
        task.loader.start = int(task.state.step) // spc
    return manifest


def _is_oom(err: Exception) -> bool:
    s = str(err)
    return "RESOURCE_EXHAUSTED" in s or "Out of memory" in s or "OOM" in s


def _require_topk(accs: dict, topk) -> None:
    """Fail fast when a requested top-k metric was never compiled into
    the eval step (shared by the train-loop eval and evaluate())."""
    for k in topk:
        if f"top{k}" not in accs:
            raise KeyError(
                f"top-{k} accuracy was not compiled into the eval step — pass "
                f"topk={tuple(topk)} to prepare_training"
            )


def _eval_and_log(task: TrainTask, batch, name: str, step: int, topk, logger: Logger):
    """Loss + top-k accuracy on one batch — ``log_loss_and_acc``
    (src/ddp_tasks.jl:128-148), computed entirely in the compiled eval
    step (replicated scalar outputs, multi-host safe)."""
    loss, accs = task.eval_fn(task.state, batch)
    _require_topk(accs, topk)
    metrics = {f"{name}_loss": float(loss)}
    for k in topk:
        metrics[f"{name}_top{k}"] = float(accs[f"top{k}"])
    logger.log(metrics, step)
    return metrics


def evaluate(
    task: TrainTask,
    dataset,
    *,
    batch_size: int = 256,
    max_batches: Optional[int] = None,
    topk: Optional[Sequence[int]] = None,
    seed: int = 0,
) -> dict:
    """Aggregate loss/top-k over a dataset with the compiled eval step —
    beyond the reference, which only ever evals a fixed 300-sample slice
    (src/ddp_tasks.jl:145).

    Coverage semantics: when the dataset supports explicit ``indices``
    and has a length, every sample is drawn EXACTLY once via sequential
    index blocks; a trailing remainder runs as one extra smaller batch
    (its own compile — shapes are static), so at most ``quantum - 1``
    samples are ever dropped, where ``quantum`` is the task's batch
    granularity: the data-axis size for most modes, raised to
    ``data_size × num_microbatches`` for pipeline tasks (whose compiled
    eval reshapes each data shard into M microbatches).  Otherwise —
    generated token streams etc. — batches are sampled and
    ``max_batches`` is required (the result is then a stochastic
    estimate, flagged by ``"exact": False``).

    Returns sample-weighted means ``{"loss": ..., "top1": ..., ...}``
    plus ``"samples"``, ``"exact"``, and (on the exact path) ``"dropped"``
    — the < quantum unreachable leftovers.  Requested top-k metrics must
    have been compiled into the eval step (``prepare_training(topk=...)``).
    """
    import inspect

    from ..data.loader import apply_transform, batch_to_dict

    if topk is None:
        # report exactly the metrics compiled into the task's eval step
        # (loss-only for the LM pipeline modes) — same default as train()
        topk = getattr(task, "topk", (1, 5, 10))

    capable = (
        hasattr(dataset, "__len__")
        and "indices" in inspect.signature(dataset.batch).parameters
    )
    n_axis = task.mesh.shape.get(mesh_lib.DATA_AXIS, 1)
    # the granularity every fed batch must divide into: the data axis,
    # raised to data_size x microbatches for pipeline tasks (their
    # compiled eval reshapes each data shard into M microbatches)
    quantum = task.batch_quantum or n_axis
    requested = batch_size
    if capable:
        # batch must stay shardable on the data axis AND inside the
        # dataset; shrink it for small datasets instead of indexing past
        # the end
        max_bs = len(dataset) // quantum * quantum
        if max_bs == 0:
            raise ValueError(
                f"dataset has {len(dataset)} samples — fewer than the "
                f"batch granularity {quantum} (data axis {n_axis}); "
                "cannot build one shardable batch"
            )
        batch_size = min(batch_size, max_bs)
    # caller-supplied sizes must land on a quantum multiple on BOTH
    # paths (indexed and sampled), or the compiled eval raises mid-run
    batch_size = batch_size // quantum * quantum
    if batch_size == 0:
        raise ValueError(
            f"batch_size {requested} rounds down to 0 at batch "
            f"granularity {quantum}; pass batch_size >= {quantum}"
        )
    rem_size = 0
    if capable:
        full_batches = len(dataset) // batch_size
        # trailing remainder, rounded to a shardable size: runs as one
        # extra smaller batch so coverage misses < quantum samples
        rem_size = (len(dataset) - full_batches * batch_size) // quantum * quantum
    if max_batches is None:
        if not hasattr(dataset, "__len__"):
            raise ValueError(
                f"{type(dataset).__name__} has no __len__; pass max_batches"
            )
        max_batches = full_batches if capable else max(1, len(dataset) // batch_size)
    if capable:
        max_batches = min(max_batches, full_batches)
        if max_batches < full_batches:
            rem_size = 0  # caller truncated: no remainder pass
    # "exact" promises once-per-sample coverage up to < n_axis leftovers —
    # a caller-truncated run is a sampled estimate of a different kind
    exact = capable and max_batches == full_batches
    rng = np.random.default_rng(seed)
    # eval goes through the eval pipeline; _eval_view never mutates the
    # caller's dataset, so a concurrent loader keeps augmenting
    dataset = _eval_view(dataset)
    total = {"loss": 0.0}
    n = 0

    def accumulate(draw, bs, first):
        nonlocal n
        draw = apply_transform(task.transform, draw)
        batch = sharding_lib.shard_batch(
            batch_to_dict(draw, getattr(dataset, "nclasses", None)), task.mesh,
            axis=getattr(task, "batch_axes", mesh_lib.DATA_AXIS),
        )
        loss, accs = task.eval_fn(task.state, batch)
        if first:
            _require_topk(accs, topk)
        total["loss"] += float(loss) * bs
        for k in topk:
            total[f"top{k}"] = (
                total.get(f"top{k}", 0.0) + float(accs[f"top{k}"]) * bs
            )
        n += bs

    for i in range(max_batches):
        if exact:
            idx = np.arange(i * batch_size, (i + 1) * batch_size)
            draw = dataset.batch(rng, batch_size, indices=idx)
        else:
            draw = dataset.batch(rng, batch_size)
        accumulate(draw, batch_size, first=i == 0)
    if exact and rem_size:
        start = max_batches * batch_size
        idx = np.arange(start, start + rem_size)
        # full_batches >= 1 on the exact path, so topk was already
        # validated by the first full batch
        accumulate(
            dataset.batch(rng, rem_size, indices=idx), rem_size, first=False
        )
    out = {key: v / max(n, 1) for key, v in total.items()}
    out["samples"] = n
    out["exact"] = exact
    if exact:
        # < quantum samples can be unreachable when the dataset size is
        # not a multiple of the batch granularity; report the honest count
        out["dropped"] = len(dataset) - n
    return out


class _PhaseClock:
    """Step-phase bracketing: every ``with phases("dispatch"):`` block
    observes its wall seconds into the registry's per-phase histogram
    and, when a tracer rides along, opens a span with the same name —
    ONE set of brackets feeds both the live ``/metrics`` percentiles
    and the offline Chrome/Perfetto timeline.  When the backend
    reports HBM truth and a watchdog rides along, every phase exit
    also samples ``device.memory_stats()`` into the watchdog's
    OOM-margin gauge/alert (:meth:`~..obs.watchdog.StepWatchdog
    .note_headroom`) — per-PHASE sampling, because the margin is
    tightest inside eval/checkpoint phases a per-step sample would
    straddle."""

    def __init__(self, observation: Observation, hbm=None):
        from ..obs.spans import phase_scope

        self.tracer = observation.tracer
        self._phase_scope = phase_scope
        # headroom sampling only when BOTH truths exist: live memory
        # stats (hbm.available — CPU short-circuits to zero cost) and
        # a watchdog to route the alert through
        self.watchdog = (observation.watchdog
                         if hbm is not None and hbm.available else None)
        self.hist = observation.registry.histogram(
            "fdtpu_train_phase_seconds",
            "wall seconds per train-step phase "
            "(data_wait/h2d/dispatch/device/eval/checkpoint)",
            labelnames=("phase",),
        )
        # per-phase seconds since the last take() — the flight
        # recorder's per-record phase breakdown (histograms are
        # cumulative; the black box needs THIS step's split)
        self.last: dict = {}

    def take(self) -> dict:
        """Return-and-clear the per-phase seconds accumulated since the
        previous call (one flight record's phase breakdown)."""
        out, self.last = self.last, {}
        return out

    @contextlib.contextmanager
    def __call__(self, name: str, **args):
        # a real span registers itself as the active phase; the
        # metrics-only path uses the lightweight registration alone so
        # the stall watchdog can still name WHERE the loop wedged
        span = (
            self.tracer.span(name, **args) if self.tracer is not None
            else self._phase_scope(name)
        )
        t0 = time.perf_counter()
        try:
            with span:
                yield
        finally:
            # observe on the exception path too (the span does): an
            # OOM-heavy run must not show artificially fast dispatch
            # percentiles while its trace shows the slow truth
            dt = time.perf_counter() - t0
            self.hist.labels(phase=name).observe(dt)
            self.last[name] = self.last.get(name, 0.0) + dt
            if self.watchdog is not None:
                from ..obs import memstats

                self.watchdog.note_headroom(memstats.min_headroom_ratio())


def train(
    task: TrainTask,
    *,
    print_every: int = 10,
    eval_every: int = 50,
    topk: Optional[Sequence[int]] = None,
    sched: Optional[Callable] = None,
    logger: Optional[Logger] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 20,
    verbose: bool = False,
    profile_dir: Optional[str] = None,
    profile_start: int = 10,
    profile_steps: int = 5,
    observation: Optional[Observation] = None,
    handle_signals: bool = False,
    guard=None,
):
    """The training loop (``train`` src/ddp_tasks.jl:174-247).

    Cadence parity: cycle print every ``print_every`` (ref 10), val+train
    eval every ``eval_every`` (ref 50) with top-k accuracy (ref k=1,5,10),
    checkpoint every ``checkpoint_every`` cycles (ref 20, src/sync.jl:156),
    OOM-skip with a live ``num_missed`` counter (ref :230-238).

    Beyond the reference (whose only timing hook is dead code, SURVEY §5):
    steps/sec + images/sec are logged at every ``print_every`` cadence,
    and ``profile_dir`` captures a ``jax.profiler`` device trace of steps
    ``[profile_start, profile_start + profile_steps)`` for TensorBoard.

    ``observation`` threads the unified observability layer
    (:mod:`fluxdistributed_tpu.obs`) through the loop.  The default
    (``None`` → :meth:`Observation.default`) is metrics-only: step
    counters, per-phase wall-time histograms, compile counts and the
    OOM-skip counter land in the process registry (scrapeable via
    ``bin/driver.py --metrics-port``) at sub-microsecond per-step cost.
    :meth:`Observation.full` additionally buffers nested phase SPANS
    (exported as Chrome/Perfetto trace JSON via ``trace_path``), runs a
    stall watchdog against the rolling-median step time, and
    ``block_until_ready``-syncs each step so device time is honestly
    attributed to a ``device`` phase.

    ``handle_signals=True`` arms checkpoint-on-preemption
    (:mod:`fluxdistributed_tpu.faults`): SIGTERM/SIGINT set a flag that
    the loop checks at the next STEP BOUNDARY (state is always
    consistent there — never mid-step, never with donated buffers in
    flight), writes a blocking sharded checkpoint plus a ``RESUME.json``
    manifest (step, data-loader cursor, skipped items, mesh topology)
    into ``checkpoint_dir``, and raises :class:`~..faults.Preempted` —
    ``bin/driver.py`` maps it to exit code 75 so a supervisor requeues
    with ``--resume``.  A resumed run (:func:`resume_training`)
    continues with step-for-step identical losses.  On multi-host runs
    the flag is agreed via :func:`..parallel.multihost.agree_to_stop`
    each step, so every host checkpoints at the same boundary.

    ``guard`` (a :class:`.guard.GuardConfig`, or ``True`` for the
    defaults) arms the self-healing policy engine
    (:class:`.guard.TrainGuard`): each step's sentinel —
    ``metrics["guard"]`` when the step was compiled with
    ``prepare_training(guard=True)``, the loss otherwise — is checked
    BEFORE the new state is committed, and the guard's verdict runs
    the ladder: quarantine-and-skip the anomalous batch (the pre-step
    state continues, exactly the OOM-skip recovery contract), roll back
    to the last-good checkpoint with the cursor rewound and the
    quarantined span recorded in the RESUME manifest, or raise
    :class:`.guard.GuardHalt` when rollbacks loop without progress.
    With a ``checkpoint_dir``, the starting state is banked as a
    baseline checkpoint (so rollback always has a target, on the
    CURRENT topology even after an elastic resume), cadence
    checkpoints become blocking and each one refreshes the manifest —
    a SIGKILL at ANY point resumes onto a consistent
    (checkpoint, cursor, quarantine) triple.  Items in
    ``task.quarantined_items`` (a resumed run's manifest) or
    ``GuardConfig.quarantine`` are skipped before dispatch — which is
    also how a clean run deterministically skips the batches a guarded
    run quarantined, the loss-parity oracle the guard tests pin.

    Resume cursor: the loop starts at ``task.loader.start`` (0 for a
    fresh run; :func:`resume_training` sets it from the manifest), and
    the loader draws batches keyed by ABSOLUTE item index — parity
    holds no matter where the run was cut.

    Returns ``(host_params, host_model_state, task)`` — the host-side
    model copy the reference returns from ``train`` (:241-246).
    """
    from .. import faults as faults_lib
    from ..parallel import multihost
    logger = logger or current_logger()
    obs = observation or Observation.default()
    reg = obs.registry
    jaxmon.install(reg)  # compile counters (idempotent, process-global)
    # per-device HBM gauges (fdtpu_hbm_bytes_* at scrape time; the
    # availability flag + NaN headroom on CPU — "unavailable", never 0)
    from ..obs import memstats as memstats_lib

    hbm = memstats_lib.HbmGauges(reg)
    phases = _PhaseClock(obs, hbm=hbm)
    steps_total = reg.counter(
        "fdtpu_train_steps_total", "optimizer steps completed")
    step_hist = reg.histogram(
        "fdtpu_train_step_seconds",
        "wall seconds per loader item (= steps_per_call optimizer steps)")
    step_gauge = reg.gauge(
        "fdtpu_train_step", "optimizer steps completed this train() call")
    oom_total = reg.counter(
        "fdtpu_train_oom_skipped_total",
        "batches skipped by OOM fault tolerance")
    sink = None
    if obs.jsonl_path:
        from ..obs import JsonlSink

        sink = JsonlSink(obs.jsonl_path, reg)
    # black-box flight recorder (obs/flight.py): per-step records that
    # survive a SIGKILL minus at most one flush interval; the dump in
    # the finally block stamps every SOFT exit's status — a footer-less
    # dump is itself the hard-death signature the postmortem keys on
    flight = obs.flight
    if flight is None and obs.flight_path:
        from ..obs.flight import FlightRecorder

        flight = FlightRecorder(obs.flight_path,
                                meta={"component": "train"})
    # the fdtpu_run_info stitch gauge: fingerprint/jax/schema labels
    # joining this registry's scrapes to flight dumps and ledger rows
    from ..obs import runs as runs_lib

    runs_lib.set_run_info(reg, "train")
    marked_steady = False
    if topk is None:
        # report exactly the metrics compiled into the task's eval step
        # (loss-only for the LM pipeline modes)
        topk = getattr(task, "topk", (1, 5, 10))
    # perf_counter, not time.time(): the loop's rate/interval math must
    # be monotonic (NTP steps or DST jumps would corrupt steps/sec and
    # the span timeline) — lint rule FDT102
    t_start = time.perf_counter()
    profiling = False
    # device loop: each loader item is K stacked batches = K optimizer
    # steps in one dispatch; cadences below tick per ITEM (= per K steps)
    spc = getattr(task, "steps_per_call", 1)
    if obs.watchdog is not None:
        obs.watchdog.start()
    if obs.tracer is not None and isinstance(task.loader, PrefetchLoader):
        # prefetch workers emit their h2d spans onto the same timeline
        # (their own thread rows in the exported trace)
        task.loader.tracer = obs.tracer

    it = iter(task.loader)
    _end = object()
    last_batch = None  # the profile artifact prices the step at these shapes
    start_item = int(getattr(task.loader, "start", 0))
    j = start_item
    t_mark, j_mark = t_start, start_item
    done_steps = 0  # optimizer steps that actually ran (skips excluded)
    preempt = faults_lib.SignalFlag().install() if handle_signals else None
    # eval and checkpoint are KNOWN-long in-loop work: suspend stall
    # detection around them (a 2 s checkpoint snapshot in a 100 ms-step
    # run must not flip /healthz to 503)
    wd_pause = (obs.watchdog.pause if obs.watchdog is not None
                else contextlib.nullcontext)

    # -- self-healing guard (train/guard.py) ---------------------------
    guard_obj = None
    if guard is not None and guard is not False:
        from .guard import GuardConfig, TrainGuard

        cfg = guard if isinstance(guard, GuardConfig) else GuardConfig()
        guard_obj = TrainGuard(cfg, registry=reg, logger=logger)
        # decisions recorded by a previous process (the RESUME manifest
        # resume_training read) replay deterministically
        for q in getattr(task, "quarantined_items", []):
            if not guard_obj.is_quarantined(q):
                guard_obj.quarantine(q)
    # the rollback target: the newest checkpoint and the loader item a
    # resume from it must start at — kept consistent with what is ON
    # DISK (only ever updated after a blocking save)
    last_good: Optional[dict] = None

    def _run_manifest(reason: str, checkpoint_step: int,
                      next_item: int) -> dict:
        m = {
            "version": 1,
            "reason": reason,
            "checkpoint_step": int(checkpoint_step),
            "next_item": int(next_item),
            "steps_per_call": spc,
            "num_missed": int(task.num_missed),
            "skipped_items": [int(x) for x in task.skipped_items],
            "mesh": {k: int(v) for k, v in dict(task.mesh.shape).items()},
            "device_count": jax.device_count(),
            "process_count": jax.process_count(),
            # how the two rng streams re-derive on resume — both are
            # keyed on restored values, so no rng state needs saving
            "rng": {
                "step": "fold_in(PRNGKey(seed), state.step), in-graph",
                "loader": "np.random.default_rng((seed, process, item))",
            },
        }
        if guard_obj is not None:
            m["quarantined_items"] = guard_obj.quarantined_items()
        return m

    def _write_guard_manifest() -> None:
        """Persist the guard's (checkpoint, cursor, quarantine) triple
        eagerly: a SIGKILL after a quarantine/rollback decision must
        resume onto the SAME decision, not re-derive the cursor from a
        step counter the skips have desynchronized."""
        if guard_obj is None or not checkpoint_dir or last_good is None:
            return
        from .checkpoint import write_resume_manifest

        write_resume_manifest(
            checkpoint_dir,
            _run_manifest("guard", last_good["step"], last_good["item"]))

    if guard_obj is not None and checkpoint_dir:
        from .checkpoint import save_checkpoint

        # bank the starting state as the first last-good checkpoint:
        # rollback needs a target from item 0 on, and re-saving on a
        # RESUMED run keeps the target on the CURRENT topology (after
        # an elastic resume, the previous run's checkpoint has the old
        # device count's ZeRO-1 flat-pad layout — rolling back onto it
        # would need the elastic path; re-banking makes every rollback
        # a plain same-topology restore)
        with wd_pause(), phases("checkpoint"):
            known = int(task.state.step)
            save_checkpoint(task.state, checkpoint_dir, known, block=True)
        last_good = {"step": known, "item": start_item}
        _write_guard_manifest()
    elif guard_obj is not None:
        logger.info(
            "guard: no checkpoint_dir — the rollback tier is disabled, "
            "the policy ladder is skip-and-quarantine -> halt")

    def _preempted() -> bool:
        if preempt is None or not handle_signals:
            return False
        hit = preempt.is_set()
        if jax.process_count() > 1:
            # every host must agree on the boundary, or one host enters
            # the collective checkpoint save the others never join
            hit = multihost.agree_to_stop(hit)
        return hit

    def _checkpoint_and_exit() -> None:
        """The checkpoint-on-signal exit: blocking sharded save + an
        atomically-written RESUME manifest, then a distinct signal to
        the caller (``Preempted`` → driver rc 75)."""
        from .checkpoint import save_checkpoint, write_resume_manifest

        step_now = int(task.state.step)
        manifest = _run_manifest(
            preempt.reason if preempt is not None else "requested",
            step_now, j)
        if checkpoint_dir:
            with wd_pause(), phases("checkpoint"):
                # blocking: the process is about to exit — an async
                # write would race the runtime teardown
                save_checkpoint(task.state, checkpoint_dir, step_now,
                                block=True)
                write_resume_manifest(checkpoint_dir, manifest)
            faults_lib.record_preemption()
            logger.info(
                f"preempted ({manifest['reason']}): checkpointed step "
                f"{step_now} + RESUME manifest (next item {j}) in "
                f"{checkpoint_dir}")
        else:
            logger.info(
                f"preempted ({manifest['reason']}) with no "
                "checkpoint_dir — nothing persisted, the run cannot "
                "be resumed")
        raise faults_lib.Preempted(
            f"training preempted at step {step_now} (item {j})",
            step=step_now, next_item=j, checkpoint_dir=checkpoint_dir,
            manifest=manifest)

    try:
        while True:
            # deterministic injection point for SIGTERM-at-step-k (the
            # fault plan delivers the signal; the very next check sees
            # it) — and THE step-boundary preemption check: state here
            # is consistent, no donated buffers are in flight
            faults_lib.fire("step", index=j)
            if _preempted():
                _checkpoint_and_exit()
            t_item = time.perf_counter()
            # data_wait: host time BLOCKED on the prefetch queue — nonzero
            # percentiles here mean the input pipeline, not the model, is
            # the bottleneck (the h2d copy itself is timed loader-side)
            with phases("data_wait"):
                batch = next(it, _end)
            if batch is _end:
                break
            last_batch = batch
            if print_every and j % print_every == 0:
                now = time.perf_counter()
                if j > j_mark:
                    # interval rates; the loop can only run ahead of the device
                    # by the dispatch queue, so interval averages are accurate
                    dsteps = (j - j_mark) * spc
                    dt = max(now - t_mark, 1e-9)
                    lead = jax.tree.leaves(batch)[0]
                    gbatch = int(lead.shape[1] if spc > 1 else lead.shape[0])
                    logger.log(
                        {
                            "steps_per_sec": round(dsteps / dt, 3),
                            "images_per_sec": round(dsteps * gbatch / dt, 1),
                        },
                        j,
                    )
                    t_mark, j_mark = now, j
                logger.info(f"cycle {j} (t={now - t_start:.1f}s)")
                if sink is not None:
                    sink.write(step=j * spc)
            if profile_dir is not None:
                if j == profile_start:
                    jax.profiler.start_trace(profile_dir)
                    profiling = True
                elif profiling and j == profile_start + profile_steps:
                    tree_lib.synchronize(task.state.params)
                    jax.profiler.stop_trace()
                    profiling = False
                    logger.info(f"profiler trace written to {profile_dir}")
            if sched is not None:
                lr = sched(j * spc)  # optimizer-step units, not loader items
                if verbose and lr is not None:
                    logger.log({"lr": float(lr)}, j)
            if (obs.steady_after is not None and not marked_steady
                    and j >= obs.steady_after):
                # warmup declared over: any further XLA compile is flagged
                # as a steady-state recompile (live metric + warning)
                jaxmon.mark_steady()
                marked_steady = True
            skipped = False
            verdict = None
            if guard_obj is not None and guard_obj.is_quarantined(j):
                # pre-step quarantine skip: the batch was drawn (the
                # data cursor must advance exactly as it did when the
                # quarantine was decided) but is never stepped — the
                # deterministic replay of a guard decision, and the
                # clean-run oracle's way to skip the same batch
                guard_obj.note_replayed_skip(j)
                logger.info(f"cycle {j}: guard — quarantined batch skipped")
                skipped = True
            else:
                # the try covers ONLY dispatch + sentinel read: recovery
                # actions (rollback restore, halt) run after it, so a
                # failure inside them can never be mistaken for a
                # skippable per-batch OOM
                try:
                    if verbose:
                        logger.info(f"  step {j}: dispatching compiled SPMD step")
                    # dispatch: host-side time to enqueue the compiled step
                    # (includes any XLA compile on first touch); with
                    # device_sync the separate device phase then holds the
                    # device execution time this step actually took
                    with phases("dispatch"):
                        new_state, metrics = task.step_fn(task.state, batch)
                        if guard_obj is None:
                            task.state = new_state
                    if obs.device_sync:
                        with phases("device"):
                            jax.block_until_ready(metrics)
                    if guard_obj is not None:
                        # verdict BEFORE commit: an anomalous step's output
                        # is discarded and the pre-step state lives on
                        verdict = guard_obj.observe(
                            j, metrics, can_rollback=last_good is not None)
                        if verdict == "ok":
                            task.state = new_state
                        else:
                            # the task mirrors the guard's record, so
                            # callers (and the preemption manifest) see
                            # decisions without reaching into guard_obj
                            task.quarantined_items = (
                                guard_obj.quarantined_items())
                            if state_donated(task.state):
                                raise RuntimeError(
                                    "guard anomaly with donate=True: the "
                                    "pre-step state was donated to the "
                                    "anomalous step and cannot be recovered "
                                    "— re-run prepare_training(donate=False)")
                except Exception as e:  # OOM-skip fault tolerance
                    if _is_oom(e):
                        if jax.process_count() > 1:
                            # Single-host-only semantics, like the reference (skip
                            # exists in task mode src/ddp_tasks.jl:230-238, NOT in
                            # process mode src/sync.jl): a one-sided skip would
                            # desynchronize step counts across hosts and strand
                            # the others in a collective this host never enters.
                            raise RuntimeError(
                                "device OOM on a multi-host run: batch skipping "
                                "cannot be coordinated one-sidedly — reduce the "
                                "per-host batch size"
                            ) from e
                        if state_donated(task.state):
                            raise RuntimeError(
                                "device OOM with donate=True: the training state was "
                                "donated to the failed step and cannot be recovered — "
                                "re-run prepare_training(donate=False) for OOM-skip"
                            ) from e
                        task.num_missed += spc
                        task.skipped_items.append(j)
                        oom_total.inc(spc)
                        # the skipped batch's GLOBAL indices go on record:
                        # the data cursor advances past it (j increments
                        # below as for any item), so a resume after this
                        # skip replays the exact same remaining stream —
                        # and the log says which samples training never saw
                        logger.log(
                            {"oom_skipped_item": j,
                             "oom_skipped_step_first": j * spc}, j)
                        logger.info(f"cycle {j}: device OOM — skipping batch ({task.num_missed} missed)")
                        skipped = True
                    else:
                        raise
            # guard verdict execution — OUTSIDE the OOM-skip try: a
            # failure while restoring a checkpoint must surface, never
            # read as "skip this batch and continue on a half-restored
            # state"
            if verdict == "skip":
                skipped = True
                _write_guard_manifest()
            elif verdict == "rollback":
                from .checkpoint import load_checkpoint, wait_for_pending

                logger.info(
                    f"guard: rolling back to checkpoint step "
                    f"{last_good['step']} (item {last_good['item']}); "
                    f"quarantined {guard_obj.quarantined_items()}")
                with wd_pause(), phases("checkpoint"):
                    wait_for_pending()
                    task.state = load_checkpoint(
                        checkpoint_dir, task.state,
                        step=last_good["step"], mesh=task.mesh)
                _write_guard_manifest()
                # rewind the data cursor and replay — the quarantined
                # span skips on the way through
                it.close()
                task.loader.start = last_good["item"]
                it = iter(task.loader)
                j = last_good["item"]
                continue
            elif verdict == "halt":
                _write_guard_manifest()
                raise guard_obj.halt(
                    "anomalies persist across "
                    f"{guard_obj._rollbacks} rollback(s)"
                    if last_good is not None else
                    "rollback needed but no checkpoint_dir to "
                    "roll back to")
            if not skipped:
                if eval_every and j % eval_every == 0:
                    with wd_pause(), phases("eval"):
                        if task.val_batch is not None:
                            _eval_and_log(task, task.val_batch, "val", j, topk, logger)
                        # chunked items carry K batches; eval the last sub-batch (the
                        # eval step is compiled for the per-step layout)
                        eb = jax.tree.map(lambda x: x[-1], batch) if spc > 1 else batch
                        _eval_and_log(task, eb, "train", j, topk, logger)
                        loss_m = metrics["loss"]
                        last_loss = loss_m[-1] if getattr(loss_m, "ndim", 0) else loss_m
                        logger.log({"train_step_loss": float(last_loss)}, j)
                if checkpoint_dir and checkpoint_every and j > 0 and j % checkpoint_every == 0:
                    from .checkpoint import save_checkpoint

                    # async write: the device→host snapshot happens now, the disk
                    # write overlaps subsequent steps (drained before exit below).
                    # Guarded runs save BLOCKING instead: last_good must only
                    # ever name a checkpoint that is durably on disk — a
                    # rollback (or a SIGKILL resume) onto a still-streaming
                    # save would read garbage the atomicity protocol hides
                    # but the cursor math would still trust
                    with wd_pause(), phases("checkpoint"):
                        save_checkpoint(task.state, checkpoint_dir,
                                        int(task.state.step),
                                        block=guard_obj is not None)
                    if guard_obj is not None:
                        last_good = {"step": int(task.state.step),
                                     "item": j + 1}
                        _write_guard_manifest()
                steps_total.inc(spc)
                done_steps += spc
                step_gauge.set(done_steps)
                step_hist.observe(time.perf_counter() - t_item)
            if obs.watchdog is not None:
                # a skipped batch is still loop progress — the watchdog
                # hunts wedged loops, not lost work (that's the counter)
                obs.watchdog.beat()
            if flight is not None:
                # the black box's per-step record: everything a
                # postmortem needs to name where and how this step went.
                # record() never raises; the assembly below must not
                # either — forensics can't be the thing that kills
                # the flight
                try:
                    frec: dict = {
                        "step": j,
                        "opt_step": done_steps,
                        "phases": {k: round(v, 4)
                                   for k, v in phases.take().items()},
                    }
                    if skipped:
                        frec["skipped"] = True
                    else:
                        try:
                            lm = metrics["loss"]
                            frec["loss"] = float(
                                lm[-1] if getattr(lm, "ndim", 0) else lm)
                        except Exception:  # noqa: BLE001
                            pass
                    if verdict is not None:
                        frec["guard_verdict"] = verdict
                        z = reg.value("fdtpu_guard_last_z")
                        if z is not None:
                            frec["guard_z"] = round(float(z), 3)
                    if hbm.available:
                        hr = memstats_lib.min_headroom_ratio()
                        if hr == hr:  # NaN = unavailable, not 0
                            frec["headroom"] = round(hr, 4)
                    compiles = reg.value("fdtpu_jax_compiles_total")
                    if compiles:
                        frec["compiles"] = int(compiles)
                    sr = reg.value("fdtpu_jax_steady_recompiles_total")
                    if sr:
                        frec["steady_recompiles"] = int(sr)
                    if task.num_missed:
                        frec["oom_skipped"] = int(task.num_missed)
                    stalled = reg.value("fdtpu_watchdog_stalled")
                    if stalled:
                        frec["stalled"] = int(stalled)
                    flight.record(**frec)
                except Exception:  # noqa: BLE001 — never kill the loop
                    pass
            j += 1
    finally:
        if flight is not None:
            # stamp every SOFT exit's verdict into the footer (a
            # SIGKILL never reaches here — the footer-less dump is
            # exactly the hard-death signature read_flight reports)
            try:
                etype, evalue = sys.exc_info()[:2]
                if etype is None:
                    flight.dump("done", steps=done_steps)
                elif issubclass(etype, faults_lib.Preempted):
                    flight.dump("preempted", error=str(evalue),
                                steps=done_steps)
                else:
                    from .guard import GuardHalt

                    flight.dump(
                        "halt" if issubclass(etype, GuardHalt)
                        else "crash",
                        error=f"{etype.__name__}: {evalue}",
                        steps=done_steps)
            except Exception:  # noqa: BLE001
                pass
        if preempt is not None:
            preempt.uninstall()
        if obs.watchdog is not None:
            obs.watchdog.stop()
        if marked_steady:
            jaxmon.clear_steady()
        if obs.tracer is not None and isinstance(task.loader, PrefetchLoader):
            task.loader.tracer = None
        if obs.tracer is not None and obs.trace_path:
            # export even on an exception: the timeline UP TO a crash
            # is exactly what the postmortem needs
            n = obs.tracer.export_chrome_trace(obs.trace_path)
            logger.info(f"span trace ({n} events) written to {obs.trace_path}")
        if obs.profile_path:
            # the planner-facing artifact: static per-layer/step costs
            # at this run's real shapes + the measured phase histograms.
            # Best-effort on purpose — a finished (or crashed) training
            # run must never be failed retroactively by its profiler
            from ..obs import profile as profile_lib

            try:
                prof = profile_lib.collect_profile(
                    task, registry=reg, batch=last_batch,
                    meta={"steps": done_steps, "steps_per_call": spc})
                prof.save(obs.profile_path)
                logger.info(f"cost profile written to {obs.profile_path}")
            except Exception as e:  # noqa: BLE001
                logger.info(f"cost profile collection failed: "
                            f"{type(e).__name__}: {e}")
        if sink is not None:
            sink.write(step=j * spc, final=True)

    if profiling:
        tree_lib.synchronize(task.state.params)
        jax.profiler.stop_trace()
        logger.info(f"profiler trace written to {profile_dir}")
    if task.num_missed:
        logger.info(f"missed {task.num_missed} batches due to OOM")
    if checkpoint_dir:
        from .checkpoint import clear_resume_manifest, wait_for_pending

        wait_for_pending()
        # a COMPLETED run must not leave a mid-run cursor behind: a
        # later --resume would trust it and skip work
        clear_resume_manifest(checkpoint_dir)
    host_params = tree_lib.to_host(task.state.params)
    host_mstate = tree_lib.to_host(task.state.model_state)
    return host_params, host_mstate, task
