"""fluxdistributed_tpu — a TPU-native data-parallel training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
``DhairyaLGandhi/FluxDistributed.jl`` (the reference): data-parallel
training of vision models on ImageNet across a device mesh, with the
input pipeline, eval/metrics, logging, checkpointing and fault handling
that surround it — built TPU-first (SPMD over ``jax.sharding.Mesh``,
compiled collectives over ICI/DCN, bf16 on the MXU) rather than as a
port of the reference's task/process + hub-reduce machinery.

The package targets full parity with the reference's exported surface
(src/FluxDistributed.jl:11-12) re-shaped for JAX; the names exported
below are the currently implemented subset.
"""

from . import compat  # noqa: F401 — must precede any jax-surface use
from . import (
    compilation,
    data,
    faults,
    mesh,
    models,
    obs,
    ops,
    optim,
    parallel,
    sharding,
    tree,
)


def __getattr__(name):
    # ``train`` is lazy (PEP 562): it imports orbax.checkpoint, which
    # costs seconds at startup that data/mesh/ops-only consumers never
    # need to pay
    if name == "train":
        import importlib

        mod = importlib.import_module(".train", __name__)
        globals()["train"] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
from .data import (
    labels,
    load_registry,
    minibatch,
    open_dataset,
    preprocess,
    register_dataset,
    train_solutions,
)
from .mesh import data_mesh, make_mesh
from .ops import logitcrossentropy, topkaccuracy, onehot
from .parallel import (
    TrainState,
    make_eval_step,
    make_train_step,
    make_train_step_shardmap,
    pmean,
    psum,
)
from .parallel.dp import flax_loss_fn

__version__ = "0.1.0"

__all__ = [
    "compilation",
    "data",
    "mesh",
    "models",
    "obs",
    "ops",
    "optim",
    "parallel",
    "sharding",
    "train",
    "tree",
    "labels",
    "load_registry",
    "minibatch",
    "open_dataset",
    "preprocess",
    "register_dataset",
    "train_solutions",
    "data_mesh",
    "make_mesh",
    "logitcrossentropy",
    "topkaccuracy",
    "onehot",
    "TrainState",
    "make_train_step",
    "make_train_step_shardmap",
    "make_eval_step",
    "flax_loss_fn",
    "pmean",
    "psum",
]
