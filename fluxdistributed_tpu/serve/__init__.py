"""Continuous-batching LM inference (the serving half of the north
star): slot-based KV cache engine, prefill/decode scheduler, and a
streaming HTTP front end — all requests flow through two compiled XLA
programs (bucketed prefill + fixed-slot decode)."""

from .engine import DEFAULT_BUCKETS, LMEngine
from .scheduler import QueueFull, Request, Scheduler
from .server import LMServer, serve_lm

__all__ = [
    "DEFAULT_BUCKETS",
    "LMEngine",
    "LMServer",
    "QueueFull",
    "Request",
    "Scheduler",
    "serve_lm",
]
