"""Fault-injection harness unit tests (fast tier).

The preemption-tolerance subsystem must be provable WITHOUT hardware or
real outages: these tests drive the injection registry, the retry
policy, backend acquisition, and the bench error-classification table
deterministically on the fake mesh (docs/robustness.md).
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time

import pytest

from fluxdistributed_tpu import faults
from fluxdistributed_tpu.obs import get_registry


# ---------------------------------------------------------------------------
# with_retries
# ---------------------------------------------------------------------------


def test_with_retries_recovers_from_transients():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return 42

    slept = []
    assert faults.with_retries(
        flaky, tries=5, backoff=0.01, sleep=slept.append) == 42
    assert calls["n"] == 3
    assert len(slept) == 2
    # exponential: second pause ~2x the first (plus bounded jitter)
    assert slept[1] > slept[0]


def test_with_retries_nonretryable_raises_immediately():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        faults.with_retries(bad, tries=5, backoff=0.0, sleep=lambda s: None)
    assert calls["n"] == 1


def test_with_retries_exhaustion_raises_budget_exceeded():
    def always():
        raise OSError("persistently transient")

    with pytest.raises(faults.RetryBudgetExceeded) as ei:
        faults.with_retries(always, tries=3, backoff=0.0,
                            sleep=lambda s: None)
    assert isinstance(ei.value.__cause__, OSError)


def test_with_retries_budget_caps_total_time():
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise OSError("transient")

    t0 = time.monotonic()
    with pytest.raises(faults.RetryBudgetExceeded):
        faults.with_retries(always, tries=100, backoff=0.05, budget=0.2)
    assert time.monotonic() - t0 < 2.0
    assert calls["n"] < 100


def test_with_retries_per_attempt_timeout():
    """A hanging attempt is bounded by ``timeout`` and classified as
    retryable (a wedged backend init, not a bug)."""
    calls = {"n": 0}

    def hang_once():
        calls["n"] += 1
        if calls["n"] == 1:
            time.sleep(5)
        return "ok"

    t0 = time.monotonic()
    out = faults.with_retries(
        hang_once, tries=2, timeout=0.2, backoff=0.0, sleep=lambda s: None)
    assert out == "ok"
    assert time.monotonic() - t0 < 3.0


def test_with_retries_custom_classifier():
    def fails():
        raise KeyError("weird")

    with pytest.raises(KeyError):
        faults.with_retries(
            fails, tries=3, backoff=0.0, sleep=lambda s: None,
            retryable=lambda e: isinstance(e, OSError))


def test_with_retries_counters_land_in_registry():
    reg = get_registry()
    before = reg.value("fdtpu_fault_retries_total", "unit_counter")

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 2:
            raise OSError("transient")
        return 1

    faults.with_retries(flaky, tries=3, backoff=0.0, sleep=lambda s: None,
                        site="unit_counter")
    assert reg.value("fdtpu_fault_retries_total", "unit_counter") == before + 1


# ---------------------------------------------------------------------------
# FaultPlan
# ---------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _clean_plan():
    yield
    faults.clear_plan()


def test_fire_is_noop_without_plan():
    faults.fire("step", index=0)
    faults.fire("loader", index=3)
    assert faults.param("local_devices") is None


def test_plan_fail_at_index_and_times():
    faults.install_plan(
        faults.FaultPlan().fail("loader", at=2, times=2))
    faults.fire("loader", index=0)  # wrong index: no trigger
    with pytest.raises(faults.FaultInjected):
        faults.fire("loader", index=2)
    with pytest.raises(faults.FaultInjected):
        faults.fire("loader", index=2)
    faults.fire("loader", index=2)  # budget spent


def test_backend_unavailable_then_recovers():
    faults.install_plan(faults.FaultPlan().backend_unavailable(2))
    devs = faults.acquire_backend(
        tries=3, timeout=None, backoff=0.0, sleep=lambda s: None)
    assert devs, "third attempt should see the real backend"


def test_from_spec_roundtrip_and_unknown_keys():
    plan = faults.FaultPlan.from_spec({
        "sigterm_at_step": 3,
        "loader_fail": {"at": 1, "times": 2},
        "backend_unavailable": 1,
        "params": {"local_devices": 4},
    })
    assert plan.params["local_devices"] == 4
    with pytest.raises(ValueError, match="unknown fault-plan keys"):
        faults.FaultPlan.from_spec({"sigsegv_at_step": 1})


def test_from_spec_generic_fail_entries():
    """The serve-side surface: the ``fail`` key addresses any
    site/action directly (router and replica fault harness)."""
    plan = faults.FaultPlan.from_spec({
        "fail": [{"site": "serve.tick", "at": 3, "times": 1},
                 {"site": "serve.dispatch", "times": 2,
                  "message": "router chaos"}],
    })
    faults.install_plan(plan)
    faults.fire("serve.tick", index=0)  # wrong index: no trigger
    with pytest.raises(faults.FaultInjected):
        faults.fire("serve.tick", index=3)
    for _ in range(2):
        with pytest.raises(faults.FaultInjected, match="router chaos"):
            faults.fire("serve.dispatch")
    faults.fire("serve.dispatch")  # budget spent
    reg = faults._metrics()
    assert reg["injected"].value("serve.tick") >= 1
    assert reg["injected"].value("serve.dispatch") >= 2


def test_from_spec_fail_entry_validation():
    with pytest.raises(ValueError, match="unknown fail-entry keys"):
        faults.FaultPlan.from_spec(
            {"fail": [{"site": "x", "when": 3}]})
    with pytest.raises(ValueError, match="needs a site"):
        faults.FaultPlan.from_spec({"fail": [{"at": 3}]})
    with pytest.raises(ValueError, match="unknown fault action"):
        faults.FaultPlan.from_spec(
            {"fail": [{"site": "x", "action": "segfault"}]})
    with pytest.raises(ValueError, match="delay must be >= 0"):
        faults.FaultPlan().fail("x", action="sleep", delay=-1)


def test_sleep_and_hang_actions_stall_then_return():
    faults.install_plan(
        faults.FaultPlan()
        .fail("slow_site", action="sleep", delay=0.05)
        .fail("hang_site", action="hang", delay=0.05))
    t0 = time.monotonic()
    faults.fire("slow_site")  # returns (slow, not raising)
    assert time.monotonic() - t0 >= 0.05
    t0 = time.monotonic()
    faults.fire("hang_site")  # explicit delay bounds the "hang" in tests
    assert time.monotonic() - t0 >= 0.05
    # with no delay a hang would stall for the documented default
    assert faults.HANG_DELAY_SECONDS >= 600


def test_value_actions_corrupt_fire_value_only():
    """nan/inf actions corrupt the OBSERVED value at fire_value sites
    (the guard's sentinel taps) and never trigger plain fire() — a
    value corruption without a value is meaningless."""
    import math

    faults.install_plan(faults.FaultPlan.from_spec({
        "fail": [{"site": "train.loss", "at": 2, "action": "nan"},
                 {"site": "train.grad", "action": "inf", "times": 2}],
    }))
    # plain fire() at a value site: no-op (would raise if matched)
    faults.fire("train.loss", index=2)
    # wrong index passes through untouched
    assert faults.fire_value("train.loss", 1.5, index=1) == 1.5
    assert math.isnan(faults.fire_value("train.loss", 1.5, index=2))
    # times budget then exhausts
    assert faults.fire_value("train.loss", 1.5, index=2) == 1.5
    for _ in range(2):
        assert math.isinf(faults.fire_value("train.grad", 0.7))
    assert faults.fire_value("train.grad", 0.7) == 0.7
    reg = faults._metrics()
    assert reg["injected"].value("train.loss") >= 1
    assert reg["injected"].value("train.grad") >= 2


def test_fire_value_noop_without_plan():
    assert faults.fire_value("train.loss", 3.25, index=0) == 3.25


def test_fire_value_delivers_side_effect_actions_too():
    """A raise planted on a sentinel site still raises through
    fire_value — the detection machinery itself can be failed."""
    faults.install_plan(
        faults.FaultPlan().fail("train.loss", message="sentinel chaos"))
    with pytest.raises(faults.FaultInjected, match="sentinel chaos"):
        faults.fire_value("train.loss", 1.0)


def test_value_action_from_spec_roundtrip_validation():
    with pytest.raises(ValueError, match="unknown fault action"):
        faults.FaultPlan().fail("x", action="nanify")
    plan = faults.FaultPlan.from_spec(
        {"fail": [{"site": "train.grad", "at": 5, "action": "inf"}]})
    faults.install_plan(plan)
    assert faults.fire_value("train.grad", 1.0, index=4) == 1.0
    assert faults.fire_value("train.grad", 1.0, index=5) == float("inf")


def test_serve_tick_site_fires_in_scheduler_step():
    """The scheduler's per-tick injection point: tick k raises inside
    step() — and the LMServer engine loop is built to survive exactly
    this (loop_errors counts it, serving continues)."""
    from fluxdistributed_tpu.serve import Scheduler
    from fluxdistributed_tpu.serve.testing import FakeLMEngine

    sched = Scheduler(FakeLMEngine(), max_queue=4)
    faults.install_plan(
        faults.FaultPlan.from_spec(
            {"fail": [{"site": "serve.tick", "at": 1}]}))
    sched.step()  # tick 0: clean
    with pytest.raises(faults.FaultInjected):
        sched.step()  # tick 1: injected
    sched.step()  # tick 2: clean again


def test_sigterm_fault_sets_signal_flag():
    """The deterministic preemption: plan fires SIGTERM at step k, a
    SignalFlag handler records it, the process survives."""
    faults.install_plan(faults.FaultPlan().sigterm_at_step(2))
    with faults.SignalFlag() as flag:
        for j in range(4):
            faults.fire("step", index=j)
            if flag.is_set():
                break
    assert flag.is_set()
    assert j == 2
    assert flag.reason == "sigterm"
    # handlers restored: SIGTERM is back to its previous disposition
    assert signal.getsignal(signal.SIGTERM) is not flag._handler


def test_signal_flag_programmatic_set():
    flag = faults.SignalFlag()
    assert not flag.is_set()
    flag.set()
    assert flag.is_set()
    assert flag.reason == "requested"


def test_signal_flag_install_off_main_thread_is_noop():
    out = {}

    def run():
        flag = faults.SignalFlag().install()
        out["installed"] = flag.installed
        flag.uninstall()

    t = threading.Thread(target=run)
    t.start()
    t.join()
    assert out["installed"] is False


# ---------------------------------------------------------------------------
# loader integration: transient assembly failures are retried
# ---------------------------------------------------------------------------


def test_loader_retries_injected_transients():
    import numpy as np

    from fluxdistributed_tpu import data_mesh
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.data.loader import PrefetchLoader

    faults.install_plan(faults.FaultPlan().loader_fail(at=1, times=2))
    loader = PrefetchLoader(
        SyntheticDataset(nsamples=32, nclasses=4, shape=(4, 4, 3)),
        data_mesh(), batch_size=8, cycles=3)
    items = list(loader)
    assert len(items) == 3  # batch 1 survived two injected failures
    # determinism: retried batch 1 equals a clean loader's batch 1
    faults.clear_plan()
    clean = list(PrefetchLoader(
        SyntheticDataset(nsamples=32, nclasses=4, shape=(4, 4, 3)),
        data_mesh(), batch_size=8, cycles=3))
    np.testing.assert_array_equal(
        np.asarray(items[1]["image"]), np.asarray(clean[1]["image"]))


def test_loader_gives_up_after_retry_budget():
    from fluxdistributed_tpu import data_mesh
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.data.loader import PrefetchLoader

    faults.install_plan(faults.FaultPlan().loader_fail(at=0, times=99))
    loader = PrefetchLoader(
        SyntheticDataset(nsamples=32, nclasses=4, shape=(4, 4, 3)),
        data_mesh(), batch_size=8, cycles=2, retries=1)
    with pytest.raises(RuntimeError, match="prefetch worker failed"):
        list(loader)


def test_loader_start_cursor_yields_same_tail():
    import numpy as np

    from fluxdistributed_tpu import data_mesh
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.data.loader import PrefetchLoader

    def make(start=0):
        return PrefetchLoader(
            SyntheticDataset(nsamples=32, nclasses=4, shape=(4, 4, 3)),
            data_mesh(), batch_size=8, cycles=4, start=start)

    full = list(make())
    tail = list(make(start=2))
    assert len(full) == 4 and len(tail) == 2
    for a, b in zip(full[2:], tail):
        np.testing.assert_array_equal(
            np.asarray(a["image"]), np.asarray(b["image"]))
    with pytest.raises(ValueError, match="past the end"):
        list(make(start=5))


# ---------------------------------------------------------------------------
# bench error classification (pure table; the harness itself is slow-tier)
# ---------------------------------------------------------------------------


def _bench_mod():
    import pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    import bench

    return bench


def test_bench_retryable_classification():
    bench = _bench_mod()
    # the backend_init phase IS the unavailability being waited out
    assert bench.retryable_error("backend_init", "anything at all")
    # unavailable/timeout signatures: retryable in any phase (a
    # compile-WINDOW expiry surfaces as a timeout signature)
    assert bench.retryable_error("compile", "measurement subprocess timed out")
    assert bench.retryable_error("measure", "UNAVAILABLE: socket closed")
    assert bench.retryable_error(
        "build", "remote_compile: read body: response body closed")
    assert bench.retryable_error("measure", "subprocess timed out after 60s")
    # real failures: not retryable — the watcher must stop hammering,
    # INCLUDING deterministic compile-phase code errors
    assert not bench.retryable_error(
        "build", "TypeError: build_step() got an unexpected keyword")
    assert not bench.retryable_error(
        "compile", "InvalidArgument: broken custom call in HLO")
    assert not bench.retryable_error(
        "measure", "AssertionError: loss is NaN")
    # the bench table and the faults default classifier share ONE
    # signature list — no drift
    from fluxdistributed_tpu.faults import UNAVAILABLE_SIGNATURES

    assert bench._unavailable_sigs() is UNAVAILABLE_SIGNATURES


def test_bench_resumable_ledger_io(tmp_path):
    bench = _bench_mod()
    path = str(tmp_path / "sub" / "ledger.json")
    bench._write_json_atomic(path, {"state": "warmed", "attempts": [1]})
    assert bench._read_json(path) == {"state": "warmed", "attempts": [1]}
    assert bench._read_json(str(tmp_path / "missing.json")) is None
    # corrupt file reads as None, never raises
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert bench._read_json(str(bad)) is None
    assert not list(tmp_path.glob("**/*.tmp.*")), "atomic writes leave no tmp"
