"""FDT301 positive: `count`/`flag` are lock-covered (accessed under
`self._lock` in `inc`) but also written with no lock held — the
read-modify-write is the error shape, the plain store the warning."""
import threading


class Stat:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.flag = False

    def inc(self):
        with self._lock:
            self.count += 1
            self.flag = True

    def racy_bump(self):
        self.count += 1  # RMW outside the lock — lost updates

    def racy_flag(self):
        self.flag = False  # unordered store against inc()'s read
