"""Fault-tolerant N-replica serving router.

A single :class:`~.server.LMServer` is a single point of failure — the
paper's own parameter-server heritage (FluxDistributed.jl's hub
all-reduce) is the cautionary tale of one coordinator wedging
everything.  This module is the robustness layer above the engine:
a stdlib-HTTP front process over N replicas that keeps serving through
replica crashes, hangs, drains, and deliberate rolling restarts.

Pieces, each independently testable without a real outage (every
failure path is drivable by :mod:`..faults` injection — sites
``serve.dispatch`` / ``serve.probe`` here, ``serve.tick`` in the
replica's scheduler):

* **health-checked replica registry** — a prober thread GETs every
  replica's ``/healthz`` each ``probe_interval``: 200 = healthy, 503
  with ``draining: true`` = *draining* (deliberately out of rotation —
  NOT a failure, the breaker ignores it), anything else counts toward a
  consecutive-failure threshold.  The same pass scrapes the replica's
  queue-wait rollup gauges off ``/metrics`` (the per-request latency
  truth `obs.reqtrace`/PR 9 put there) for least-loaded dispatch.
* **per-replica circuit breakers** — closed → open after
  ``failure_threshold`` consecutive probe/dispatch failures; after
  ``breaker_cooldown`` seconds the breaker half-opens and admits ONE
  trial request at a time (a probe success also closes it — the
  deterministic recovery path when healthy replicas are absorbing the
  traffic).  State rides the ``fdtpu_router_breaker_state`` gauge
  (0 closed / 1 half-open / 2 open) per replica.
* **dispatch with failover** — requests route to the replica with the
  lowest queue-wait p50 (ties broken by occupancy then round-robin;
  stale metrics fall back to pure round-robin).  A dispatch that dies
  before its first byte/token is transparently retried on another
  replica through :func:`..faults.with_retries` (site
  ``serve.dispatch`` — the one retry policy in the tree); once a
  streamed token has been forwarded the router fails FAST with the
  replica named (re-issuing would duplicate tokens).  The client's
  ``X-Request-Id`` (or a router-minted one) rides every hop, so a
  failed-over request appears on BOTH replicas' ``/trace`` timelines
  under one id and the stitched view tells the whole story.
* **rolling restarts** — :meth:`Router.rolling_restart` takes the fleet
  through drain → restart → ready, ONE replica at a time: the replica
  is pulled from dispatch, router-side in-flight requests to it
  complete, its ``restart`` hook (SIGTERM-drain + respawn for
  supervised subprocess replicas) brings a successor up, and traffic
  only moves on once the successor probes healthy.  With replicas
  started from the AOT executable pool (``bin/serve.py --aot-dir`` /
  ``--prewarm``, :mod:`..compilation`) the successor skips tracing and
  compiling — near-zero-downtime redeploys.
* **fleet rollup** — ``GET /metrics`` re-exposes every replica's series
  with an added ``replica="<name>"`` label (names stay byte-identical
  to a direct scrape — aggregation semantics stay correct because no
  lossy sum is baked in) plus the router's own ``fdtpu_router_*``
  series; ``GET /healthz`` rolls up per-replica state; ``GET /trace``
  stitches the fleet's Perfetto timelines into one document (one
  process row per replica).
"""

from __future__ import annotations

import http.client
import http.server
import itertools
import json
import math
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from .. import faults
from ..obs.metrics import Registry

__all__ = [
    "NoReplicaAvailable",
    "Replica",
    "Router",
    "RouterError",
    "SupervisedReplica",
]

#: every router-owned series carries this prefix (FDT106-policed, like
#: the scheduler's METRIC_PREFIX)
METRIC_PREFIX = "fdtpu_router_"

#: breaker states as the fdtpu_router_breaker_state gauge renders them
BREAKER_STATES = {"closed": 0, "half_open": 1, "open": 2}

_request_ids = itertools.count()


class RouterError(RuntimeError):
    """Router operational failure (bad configuration, restart hook
    missing/failed)."""


class NoReplicaAvailable(RuntimeError):
    """No replica is currently dispatchable (all dead, draining,
    restarting, or circuit-open) — retried by the dispatch policy,
    HTTP 503 when retries exhaust."""


class _DispatchFailed(RuntimeError):
    """One dispatch attempt failed in a way another replica can absorb
    (connection error, 429, draining 503) — the retryable marker
    :func:`..faults.with_retries` keys on."""


@dataclass(eq=False)  # identity semantics: replicas live in sets/dicts
class Replica:
    """One replica's registry entry: identity, health/breaker state,
    and the load truth the prober scraped last."""

    name: str
    url: str  # base, e.g. http://127.0.0.1:8001 (no trailing slash)
    #: optional restart hook for rolling restarts: called with this
    #: Replica, must gracefully stop the backing process/server and
    #: bring a successor up, returning the (possibly new) base url
    restart: Optional[Callable[["Replica"], str]] = None

    # -- prober-owned state --------------------------------------------
    healthy: bool = False
    draining: bool = False
    restarting: bool = False
    consecutive_failures: int = 0
    last_error: Optional[str] = None
    last_probe_at: float = 0.0

    # -- circuit breaker -----------------------------------------------
    breaker: str = "closed"
    opened_at: float = 0.0
    trial_inflight: bool = False

    # -- load truth (least-loaded dispatch) ----------------------------
    queue_wait_p50: float = math.nan
    queue_depth: int = 0
    active_slots: int = 0
    load_at: float = 0.0  # monotonic stamp of the last metrics scrape

    # -- router-side bookkeeping ---------------------------------------
    inflight: int = 0

    def __post_init__(self):
        self.url = self.url.rstrip("/")


class Router:
    """The N-replica front process.  Lifecycle::

        router = Router([Replica("r0", url0), Replica("r1", url1)])
        router.start_probes()             # health/load prober thread
        httpd = router.serve("0.0.0.0", 8100)
        httpd.serve_forever()

    ``registry=None`` builds a PRIVATE metrics registry per router (the
    scheduler convention — tests spin several per process).
    """

    def __init__(self, replicas: Sequence[Replica] = (), *,
                 probe_interval: float = 0.5,
                 probe_timeout: float = 2.0,
                 failure_threshold: int = 3,
                 breaker_cooldown: float = 2.0,
                 metrics_stale_after: float = 3.0,
                 dispatch_tries: int = 3,
                 dispatch_backoff: float = 0.05,
                 upstream_timeout: float = 600.0,
                 registry: Optional[Registry] = None):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        if dispatch_tries < 1:
            raise ValueError(
                f"dispatch_tries must be >= 1, got {dispatch_tries}")
        self.probe_interval = probe_interval
        self.probe_timeout = probe_timeout
        self.failure_threshold = failure_threshold
        self.breaker_cooldown = breaker_cooldown
        self.metrics_stale_after = metrics_stale_after
        self.dispatch_tries = dispatch_tries
        self.dispatch_backoff = dispatch_backoff
        self.upstream_timeout = upstream_timeout
        self._replicas: List[Replica] = []
        self._lock = threading.RLock()
        self._rr = -1  # round-robin cursor
        self._probe_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._probe_index = 0  # running count, the serve.probe index
        self._dispatch_index = 0  # running count, the serve.dispatch index
        self.bound_port: Optional[int] = None

        r, p = (registry if registry is not None else Registry(),
                METRIC_PREFIX)
        self.registry = r
        self._c_requests = r.counter(
            p + "requests_total", "requests handled", labelnames=("code",))
        self._c_dispatches = r.counter(
            p + "dispatches_total", "upstream dispatch attempts",
            labelnames=("replica",))
        self._c_dispatch_failures = r.counter(
            p + "dispatch_failures_total",
            "dispatch attempts that failed over / errored",
            labelnames=("replica",))
        self._c_failovers = r.counter(
            p + "failovers_total",
            "requests that completed only via a retry on another attempt")
        self._c_midstream = r.counter(
            p + "midstream_failures_total",
            "streams cut after the first token (fail-fast, not retried)")
        self._c_probes = r.counter(
            p + "probes_total", "health probes", labelnames=("result",))
        self._c_breaker_opens = r.counter(
            p + "breaker_opens_total", "circuit-breaker open transitions",
            labelnames=("replica",))
        self._c_restarts = r.counter(
            p + "restarts_total", "replica restarts completed",
            labelnames=("replica",))
        self._c_scrape_failures = r.counter(
            p + "rollup_scrape_failures_total",
            "replica /metrics//trace scrapes that failed during a rollup")
        self._h_dispatch = r.histogram(
            p + "dispatch_seconds",
            "wall time of one successful upstream dispatch")
        self._g_breaker = r.gauge(
            p + "breaker_state",
            "per-replica breaker: 0 closed, 1 half-open, 2 open",
            labelnames=("replica",))
        self._g_healthy = r.gauge(
            p + "replica_healthy", "1 when the last probe succeeded",
            labelnames=("replica",))
        g = r.gauge
        g(p + "replicas", "registered replicas").set_function(
            lambda: len(self._replicas))
        g(p + "replicas_dispatchable",
          "replicas dispatch would consider right now").set_function(
            lambda: self._dispatchable_count())
        g(p + "inflight",
          "requests currently proxied to some replica").set_function(
            lambda: sum(rep.inflight for rep in self._replicas))
        self._callback_gauges = [
            p + k for k in ("replicas", "replicas_dispatchable", "inflight")]
        for rep in replicas:
            self.add_replica(rep)

    # ---- registry management ----------------------------------------------

    def add_replica(self, rep: Replica) -> Replica:
        with self._lock:
            if any(x.name == rep.name for x in self._replicas):
                raise RouterError(f"duplicate replica name {rep.name!r}")
            self._replicas.append(rep)
            self._g_breaker.labels(replica=rep.name).set(
                BREAKER_STATES[rep.breaker])
            self._g_healthy.labels(replica=rep.name).set(0)
        return rep

    @property
    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas)

    def close(self) -> None:
        """Stop the prober and detach this router's scrape callbacks
        (the shared-registry retirement path, as ``Scheduler.close``)."""
        self.stop_probes()
        for name in self._callback_gauges:
            self.registry.unregister(name)

    # ---- breaker -----------------------------------------------------------

    def _set_breaker(self, rep: Replica, state: str) -> None:
        """Lock held by caller.  One gauge write per transition."""
        if rep.breaker == state:
            return
        if state == "open":
            rep.opened_at = time.monotonic()
            self._c_breaker_opens.labels(replica=rep.name).inc()
        rep.breaker = state
        rep.trial_inflight = False
        self._g_breaker.labels(replica=rep.name).set(BREAKER_STATES[state])

    def _record_failure(self, rep: Replica, err: str) -> None:
        with self._lock:
            rep.consecutive_failures += 1
            rep.last_error = err
            if rep.breaker == "half_open":
                self._set_breaker(rep, "open")  # trial failed: re-open
            elif (rep.breaker == "closed"
                  and rep.consecutive_failures >= self.failure_threshold):
                self._set_breaker(rep, "open")

    def _record_success(self, rep: Replica) -> None:
        with self._lock:
            rep.consecutive_failures = 0
            rep.last_error = None
            if rep.breaker != "closed":
                self._set_breaker(rep, "closed")

    # ---- probing -----------------------------------------------------------

    def start_probes(self) -> None:
        """One synchronous sweep (so the first dispatch after start sees
        real health), then the background prober thread."""
        if self._probe_thread is not None:
            return
        self.probe_now()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="router-probe", daemon=True)
        self._probe_thread.start()

    def stop_probes(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=10)
            self._probe_thread = None
        self._stop.clear()

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            self.probe_now()
            self._stop.wait(self.probe_interval)

    def probe_now(self) -> None:
        """One probe sweep over the fleet (also the deterministic test
        hook — returns only when every probe finished).  Replicas are
        probed CONCURRENTLY: one wedged replica blocking its full
        probe_timeout must not stall health detection — or stale out
        the load scrapes — for the rest of the fleet."""
        todo = [rep for rep in self.replicas if not rep.restarting]
        if not todo:
            return
        if len(todo) == 1:
            self._probe_one(todo[0])
            return
        threads = [threading.Thread(target=self._probe_one, args=(rep,),
                                    daemon=True) for rep in todo]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def _probe_one(self, rep: Replica) -> bool:
        with self._lock:  # deterministic fault indices under concurrency
            idx = self._probe_index
            self._probe_index += 1
        try:
            faults.fire("serve.probe", index=idx)
            body = self._http_json("GET", rep.url + "/healthz",
                                   timeout=self.probe_timeout)
            ok, draining = bool(body.get("ok")), bool(body.get("draining"))
        except _UpstreamHTTPError as e:
            # an HTTP response IS a live replica; only 503+draining is
            # the deliberate out-of-rotation signal, anything else is a
            # real failure (e.g. a dead engine loop behind /healthz)
            try:
                draining = bool(json.loads(e.body).get("draining"))
            except (ValueError, AttributeError):
                draining = False
            if e.code == 503 and draining:
                ok = True  # deliberate: breaker must NOT count it
            else:
                return self._probe_failed(rep, f"HTTP {e.code}")
        except Exception as e:  # noqa: BLE001 — any transport failure
            return self._probe_failed(rep, f"{type(e).__name__}: {e}")
        with self._lock:
            rep.last_probe_at = time.monotonic()
            rep.draining = draining
            rep.healthy = ok and not draining
            self._g_healthy.labels(replica=rep.name).set(
                1 if rep.healthy else 0)
        if draining:
            self._c_probes.labels(result="draining").inc()
            return True
        self._c_probes.labels(result="ok").inc()
        self._record_success(rep)
        self._scrape_load(rep)
        return True

    def _probe_failed(self, rep: Replica, err: str) -> bool:
        self._c_probes.labels(result="fail").inc()
        with self._lock:
            rep.last_probe_at = time.monotonic()
            rep.healthy = False
            rep.draining = False
            self._g_healthy.labels(replica=rep.name).set(0)
        self._record_failure(rep, err)
        return False

    def _scrape_load(self, rep: Replica) -> None:
        """Pull the least-loaded inputs off the replica's /metrics: the
        queue-wait p50 rollup gauge plus occupancy.  Best-effort — a
        failed scrape just leaves the load stale (round-robin covers
        it); it never counts toward the breaker (the probe that just
        succeeded is the liveness truth)."""
        try:
            text = self._http_text("GET", rep.url + "/metrics",
                                   timeout=self.probe_timeout)
        except Exception:  # noqa: BLE001
            self._c_scrape_failures.inc()
            return
        vals = _parse_gauges(text, (
            "fdtpu_serve_queue_wait_sec_p50",
            "fdtpu_serve_queue_depth",
            "fdtpu_serve_active_slots",
        ))
        with self._lock:
            rep.queue_wait_p50 = vals.get(
                "fdtpu_serve_queue_wait_sec_p50", math.nan)
            rep.queue_depth = int(vals.get("fdtpu_serve_queue_depth", 0))
            rep.active_slots = int(vals.get("fdtpu_serve_active_slots", 0))
            rep.load_at = time.monotonic()

    # ---- dispatch ----------------------------------------------------------

    def _dispatchable(self, rep: Replica, now: float) -> bool:
        """Lock held by caller.  Would pick() consider this replica?"""
        if rep.draining or rep.restarting:
            return False
        if rep.breaker == "open":
            if now - rep.opened_at < self.breaker_cooldown:
                return False
            self._set_breaker(rep, "half_open")
        if rep.breaker == "half_open":
            return not rep.trial_inflight
        return rep.healthy

    def _dispatchable_count(self) -> int:
        now = time.monotonic()
        with self._lock:
            return sum(self._dispatchable(rep, now) for rep in self._replicas)

    def _pick(self, exclude) -> Replica:
        """Choose a replica and claim one in-flight ticket on it.

        Least-loaded by queue-wait p50 (NaN = no waits recorded yet =
        unloaded) with occupancy then round-robin tie-breaks, when every
        candidate's load scrape is fresh; pure round-robin otherwise.
        Half-open replicas are only used when no closed one is
        available — the trial request that would re-close the breaker
        must not jump the healthy fleet's queue."""
        now = time.monotonic()
        with self._lock:
            cands = [rep for rep in self._replicas
                     if rep not in exclude and self._dispatchable(rep, now)]
            closed = [rep for rep in cands if rep.breaker == "closed"]
            pool = closed or cands
            if not pool:
                raise NoReplicaAvailable(
                    "no dispatchable replica (dead, draining, restarting "
                    "or circuit-open); fleet size "
                    f"{len(self._replicas)}")
            fresh = all(now - rep.load_at <= self.metrics_stale_after
                        for rep in pool)
            # rotate so round-robin (and least-loaded ties) spread load
            start = (self._rr + 1) % len(pool)
            rotated = pool[start:] + pool[:start]
            if fresh:
                def load_key(rep: Replica):
                    p50 = rep.queue_wait_p50
                    return (0.0 if math.isnan(p50) else p50,
                            rep.queue_depth + rep.active_slots + rep.inflight)
                chosen = min(rotated, key=load_key)
            else:
                chosen = rotated[0]
            self._rr = pool.index(chosen)
            chosen.inflight += 1
            if chosen.breaker == "half_open":
                chosen.trial_inflight = True
            return chosen

    def _release(self, rep: Replica) -> None:
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)
            rep.trial_inflight = False

    def dispatch(self, payload: bytes, rid: str, stream: bool):
        """Route one /v1/generate body.  Returns either
        ``("json", code, body_bytes, replica_name)`` (response fully
        read — safe to have retried at any point) or
        ``("stream", response, first_line, replica_name)`` where
        ``response`` is the still-open upstream response positioned
        AFTER its first emitted line: everything up to and including the
        first token was covered by failover, everything after is the
        caller's fail-fast region.

        Raises :class:`..faults.RetryBudgetExceeded` when every attempt
        failed (``__cause__`` holds the last failure) — the HTTP layer
        maps it to 502/503."""
        exclude: set = set()
        state = {"attempts": 0}

        def attempt():
            with self._lock:  # deterministic fault indices
                idx = self._dispatch_index
                self._dispatch_index += 1
            state["attempts"] += 1
            faults.fire("serve.dispatch", index=idx)
            rep = self._pick(exclude)
            self._c_dispatches.labels(replica=rep.name).inc()
            t0 = time.monotonic()
            req = urllib.request.Request(
                rep.url + "/v1/generate", data=payload, method="POST",
                headers={"Content-Type": "application/json",
                         "X-Request-Id": rid})
            try:
                resp = urllib.request.urlopen(
                    req, timeout=self.upstream_timeout)
                if stream:
                    # the first line is the first token (or the terminal
                    # done/error line): reading it INSIDE the attempt
                    # keeps pre-first-token deaths retryable
                    first = resp.readline()
                    if not first:
                        raise ConnectionError(
                            "replica closed the stream before any token")
                    self._h_dispatch.observe(time.monotonic() - t0)
                    self._record_success(rep)
                    return ("stream", resp, first, rep)
                body = resp.read()
                code = resp.status
            except urllib.error.HTTPError as e:
                body = e.read()
                code = e.code
                if code == 503 and _body_draining(body):
                    # deliberate drain: route around, no breaker count
                    with self._lock:
                        rep.draining = True
                        rep.healthy = False
                    self._release(rep)
                    exclude.add(rep)
                    raise _DispatchFailed(
                        f"replica {rep.name} is draining") from e
                if code == 429:
                    # backpressure: the replica is healthy, just full —
                    # another replica may have room, so fail over
                    # without feeding the breaker
                    self._release(rep)
                    exclude.add(rep)
                    raise _DispatchFailed(
                        f"replica {rep.name} admission queue full") from e
                if code >= 500:
                    # a 5xx is the REPLICA's failure: nothing reached
                    # the client yet, so fail over — and feed the
                    # breaker instead of resetting it
                    self._release(rep)
                    self._c_dispatch_failures.labels(
                        replica=rep.name).inc()
                    self._record_failure(rep, f"HTTP {code}")
                    exclude.add(rep)
                    raise _DispatchFailed(
                        f"replica {rep.name} answered HTTP {code}") from e
                # 4xx: the CLIENT's error — passthrough, and the
                # replica answering at all is a liveness success
            except (OSError, urllib.error.URLError,
                    http.client.HTTPException) as e:
                # connection refused/reset, timeouts, half-written
                # responses: the replica-died-under-us family — count it
                # against the breaker and fail over
                self._release(rep)
                self._c_dispatch_failures.labels(replica=rep.name).inc()
                self._record_failure(rep, f"{type(e).__name__}: {e}")
                exclude.add(rep)
                raise _DispatchFailed(
                    f"replica {rep.name} ({rep.url}) failed before first "
                    f"token: {type(e).__name__}: {e}") from e
            self._h_dispatch.observe(time.monotonic() - t0)
            self._record_success(rep)
            # every "json" return is fully read — the ticket is done
            # (the stream return keeps it until the forward finishes)
            self._release(rep)
            return ("json", code, body, rep)

        result = faults.with_retries(
            attempt,
            tries=self.dispatch_tries,
            backoff=self.dispatch_backoff,
            site="serve.dispatch",
            retryable=lambda e: isinstance(
                e, (_DispatchFailed, NoReplicaAvailable,
                    faults.FaultInjected)),
        )
        if state["attempts"] > 1:
            self._c_failovers.inc()
        return result

    # ---- rollups -----------------------------------------------------------

    def health(self) -> dict:
        """The /healthz rollup: ok iff at least one replica is
        dispatchable, plus the full per-replica state table."""
        now = time.monotonic()
        entries = []
        with self._lock:
            reps = list(self._replicas)
            for rep in reps:
                p50 = rep.queue_wait_p50
                entries.append({
                    "name": rep.name,
                    "url": rep.url,
                    "healthy": rep.healthy,
                    "draining": rep.draining,
                    "restarting": rep.restarting,
                    "breaker": rep.breaker,
                    "consecutive_failures": rep.consecutive_failures,
                    "inflight": rep.inflight,
                    "queue_depth": rep.queue_depth,
                    "active_slots": rep.active_slots,
                    "queue_wait_sec_p50": (
                        None if math.isnan(p50) else p50),
                    "load_stale": now - rep.load_at
                    > self.metrics_stale_after,
                    "last_error": rep.last_error,
                })
        dispatchable = self._dispatchable_count()
        return {
            "ok": dispatchable > 0,
            "role": "router",
            "replicas": entries,
            "dispatchable": dispatchable,
        }

    def metrics_text(self) -> str:
        """The fleet /metrics rollup: every replica's exposition with an
        injected ``replica="<name>"`` label — series NAMES byte-identical
        to a direct replica scrape (parity-pinned in tests) — followed by
        the router's own registry."""
        fams: Dict[str, dict] = {}
        order: List[str] = []
        for rep in self.replicas:
            try:
                text = self._http_text("GET", rep.url + "/metrics",
                                       timeout=self.probe_timeout)
            except Exception:  # noqa: BLE001 — a dead replica must not
                self._c_scrape_failures.inc()  # kill the fleet scrape
                continue
            _merge_exposition(fams, order, text, rep.name)
        lines = []
        for name in order:
            fam = fams[name]
            if fam["help"]:
                lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['type']}")
            lines.extend(fam["samples"])
        head = "\n".join(lines)
        return (head + "\n" if head else "") + self.registry.prometheus_text()

    def trace_document(self) -> dict:
        """The stitched fleet timeline: each replica's /trace document
        re-rooted on its own ``pid`` row (replica name in the process
        label), so one Perfetto load shows every replica's request
        tracks side by side — including a failed-over request's id on
        both its replicas."""
        events: List[dict] = []
        info = []
        for pid, rep in enumerate(self.replicas, start=1):
            try:
                doc = self._http_json("GET", rep.url + "/trace",
                                      timeout=self.probe_timeout)
            except _UpstreamHTTPError as e:
                info.append({"name": rep.name, "url": rep.url,
                             "error": f"HTTP {e.code}"})
                continue
            except Exception as e:  # noqa: BLE001
                self._c_scrape_failures.inc()
                info.append({"name": rep.name, "url": rep.url,
                             "error": f"{type(e).__name__}: {e}"})
                continue
            n = 0
            for ev in doc.get("traceEvents", []):
                ev = dict(ev)
                ev["pid"] = pid
                if ev.get("ph") == "M" and ev.get("name") == "process_name":
                    ev["args"] = {
                        "name": f"replica {rep.name} ({rep.url})"}
                events.append(ev)
                n += 1
            other = doc.get("otherData", {})
            info.append({"name": rep.name, "url": rep.url, "pid": pid,
                         "events": n,
                         "dropped_events": other.get("dropped_events", 0)})
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "fluxdistributed_tpu.serve.router",
                "replicas": info,
            },
        }

    # ---- rolling restart ---------------------------------------------------

    def rolling_restart(self, drain_timeout: float = 30.0,
                        ready_timeout: float = 120.0,
                        poll: float = 0.05) -> List[dict]:
        """Restart every replica, one at a time, with traffic routed
        around the one in hand:

        1. mark it ``restarting`` (dispatch skips it from this instant);
        2. wait (bounded by ``drain_timeout``) for router-side in-flight
           requests to it to finish;
        3. call its ``restart`` hook — for supervised subprocess
           replicas that is SIGTERM (the replica's own graceful drain
           finishes anything the router didn't see) + respawn;
        4. probe until the successor reports healthy (bounded by
           ``ready_timeout``) before moving to the next replica.

        Returns one summary dict per replica.  Raises
        :class:`RouterError` if any replica lacks a restart hook or its
        successor never comes healthy — the fleet is left with the
        completed restarts in place."""
        with self._lock:
            reps = list(self._replicas)
            missing = [rep.name for rep in reps if rep.restart is None]
        if missing:
            raise RouterError(
                f"replicas {missing} have no restart hook — rolling "
                "restart needs supervised replicas (bin/router.py "
                "--spawn) or Replica(restart=...) callables")
        results = []
        for rep in reps:
            t0 = time.monotonic()
            with self._lock:
                rep.restarting = True
            deadline = t0 + drain_timeout
            while rep.inflight > 0 and time.monotonic() < deadline:
                time.sleep(poll)
            drained = rep.inflight == 0
            try:
                new_url = rep.restart(rep).rstrip("/")
            except Exception as e:
                with self._lock:
                    rep.restarting = False
                raise RouterError(
                    f"restart hook for replica {rep.name} failed: "
                    f"{type(e).__name__}: {e}") from e
            with self._lock:
                rep.url = new_url
                rep.consecutive_failures = 0
                rep.healthy = False
                rep.draining = False
                rep.load_at = 0.0
                self._set_breaker(rep, "closed")
            ready_deadline = time.monotonic() + ready_timeout
            while time.monotonic() < ready_deadline:
                if self._probe_one(rep) and rep.healthy:
                    break
                time.sleep(max(poll, 0.1))
            with self._lock:
                rep.restarting = False
            if not rep.healthy:
                raise RouterError(
                    f"replica {rep.name} did not come back healthy at "
                    f"{new_url} within {ready_timeout}s")
            self._c_restarts.labels(replica=rep.name).inc()
            results.append({
                "replica": rep.name,
                "url": new_url,
                "drained_clean": drained,
                "seconds": round(time.monotonic() - t0, 3),
            })
        return results

    # ---- HTTP plumbing -----------------------------------------------------

    @staticmethod
    def _http_text(method: str, url: str, timeout: float,
                   data: Optional[bytes] = None) -> str:
        req = urllib.request.Request(url, data=data, method=method)
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.read().decode("utf-8", "replace")

    @staticmethod
    def _http_json(method: str, url: str, timeout: float,
                   data: Optional[bytes] = None) -> dict:
        req = urllib.request.Request(url, data=data, method=method)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            raise _UpstreamHTTPError(e.code, e.read()) from e

    # ---- the front HTTP server --------------------------------------------

    def make_handler(self):
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code, body: bytes, ctype: str, rid=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                if rid:
                    self.send_header("X-Request-Id", rid)
                self.end_headers()
                self.wfile.write(body)
                outer._c_requests.labels(code=str(code)).inc()

            def _send_json(self, code, obj, rid=None):
                self._send(code, json.dumps(obj).encode(),
                           "application/json", rid=rid)

            def do_GET(self):
                if self.path == "/healthz":
                    h = outer.health()
                    self._send_json(200 if h["ok"] else 503, h)
                elif self.path == "/metrics":
                    self._send(200, outer.metrics_text().encode(),
                               "text/plain; version=0.0.4")
                elif self.path == "/trace":
                    self._send_json(200, outer.trace_document())
                elif self.path == "/admin/replicas":
                    self._send_json(200, outer.health()["replicas"])
                else:
                    self._send_json(404, {"error": "not found"})

            def do_POST(self):
                if self.path == "/v1/generate":
                    self._generate()
                elif self.path == "/admin/rolling_restart":
                    self._rolling_restart()
                elif self.path == "/admin/probe":
                    outer.probe_now()
                    self._send_json(200, outer.health())
                else:
                    self._send_json(404, {"error": "not found"})

            def _rolling_restart(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                    results = outer.rolling_restart(
                        drain_timeout=float(body.get("drain_timeout", 30.0)),
                        ready_timeout=float(
                            body.get("ready_timeout", 120.0)))
                except RouterError as e:
                    self._send_json(500, {"error": str(e)})
                    return
                except (ValueError, TypeError) as e:
                    self._send_json(400, {"error": str(e)})
                    return
                self._send_json(200, {"restarted": results})

            def _generate(self):
                n = int(self.headers.get("Content-Length", 0))
                payload = self.rfile.read(n)
                try:
                    body = json.loads(payload or b"{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be a JSON object")
                except ValueError as e:
                    self._send_json(400, {"error": str(e)})
                    return
                # the correlation id that stitches router logs to every
                # replica timeline this request touches: the client's,
                # or a router-minted one
                rid = str(self.headers.get("X-Request-Id")
                          or f"rt-{next(_request_ids)}-"
                             f"{uuid.uuid4().hex[:8]}")[:128]
                stream = bool(body.get("stream", False))
                try:
                    result = outer.dispatch(payload, rid, stream)
                except faults.RetryBudgetExceeded as e:
                    cause = e.__cause__
                    code = (503 if isinstance(cause, NoReplicaAvailable)
                            else 502)
                    self._send_json(code, {
                        "error": str(cause) if cause else str(e),
                        "request_id": rid,
                    }, rid=rid)
                    return
                if result[0] == "json":
                    _, code, data, rep = result
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.send_header("X-Request-Id", rid)
                    self.send_header("X-Fdtpu-Replica", rep.name)
                    self.end_headers()
                    self.wfile.write(data)
                    outer._c_requests.labels(code=str(code)).inc()
                    return
                _, resp, first, rep = result
                self._forward_stream(resp, first, rep, rid)

            def _forward_stream(self, resp, first: bytes, rep, rid: str):
                """Forward the already-open upstream stream.  The first
                token was read inside the (retryable) dispatch; from
                here an upstream death fails FAST with the replica
                named — tokens already forwarded cannot be replayed."""

                def chunk(data: bytes):
                    self.wfile.write(f"{len(data):x}\r\n".encode())
                    self.wfile.write(data + b"\r\n")
                    self.wfile.flush()

                code = 200
                try:
                    # header writes sit INSIDE the release scope: a
                    # client that vanished already would otherwise leak
                    # the replica's inflight ticket and the open
                    # upstream response
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/jsonlines")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.send_header("X-Request-Id", rid)
                    self.send_header("X-Fdtpu-Replica", rep.name)
                    self.end_headers()
                    # upstream reads and downstream writes fail for
                    # DIFFERENT parties: only a read failure is the
                    # replica's fault (breaker + fail-fast error line);
                    # a write failure is the client leaving (499, and
                    # the replica stays innocent)
                    upstream_err = None
                    line = first
                    while line:
                        chunk(line)  # downstream: errors escape to 499
                        try:
                            line = resp.readline()
                        except (OSError,
                                http.client.HTTPException) as e:
                            upstream_err = e
                            break
                    if upstream_err is not None:
                        # mid-stream upstream death: no transparent
                        # retry possible, say exactly who died
                        outer._c_midstream.inc()
                        outer._c_dispatch_failures.labels(
                            replica=rep.name).inc()
                        outer._record_failure(
                            rep, f"mid-stream: "
                                 f"{type(upstream_err).__name__}: "
                                 f"{upstream_err}")
                        code = 502
                        chunk((json.dumps({
                            "done": False,
                            "error": f"replica {rep.name} ({rep.url}) "
                                     f"failed mid-stream after first "
                                     f"token: "
                                     f"{type(upstream_err).__name__}: "
                                     f"{upstream_err}",
                            "replica": rep.name,
                            "request_id": rid,
                        }) + "\n").encode())
                    chunk(b"")  # terminal zero-length chunk
                except (BrokenPipeError, ConnectionResetError):
                    code = 499  # client went away; nginx's convention
                finally:
                    try:
                        resp.close()
                    except OSError:
                        pass
                    outer._release(rep)
                    outer._c_requests.labels(code=str(code)).inc()

        return Handler

    def serve(self, host: str = "127.0.0.1", port: int = 8100):
        """Build the front ThreadingHTTPServer (probes started); caller
        runs ``serve_forever`` — the LMServer pattern."""
        self.start_probes()
        httpd = http.server.ThreadingHTTPServer((host, port),
                                                self.make_handler())
        self.bound_port = httpd.server_address[1]
        return httpd


class _UpstreamHTTPError(RuntimeError):
    """An upstream replied with an HTTP error status (body preserved)."""

    def __init__(self, code: int, body: bytes):
        super().__init__(f"HTTP {code}")
        self.code = code
        self.body = body


def _body_draining(body: bytes) -> bool:
    try:
        return bool(json.loads(body).get("draining"))
    except (ValueError, AttributeError):
        return False


def _parse_gauges(text: str, names) -> Dict[str, float]:
    """Pull unlabeled series values out of exposition text (the load
    scrape: three gauges off a multi-KB page, no full parse needed)."""
    want = set(names)
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        series, _, value = line.rpartition(" ")
        if series in want:
            try:
                out[series] = float(value)
            except ValueError:
                pass
    return out


def _inject_replica_label(series: str, replica: str) -> str:
    esc = replica.replace("\\", "\\\\").replace('"', '\\"')
    i = series.find("{")
    if i == -1:
        return f'{series}{{replica="{esc}"}}'
    return f'{series[:i]}{{replica="{esc}",{series[i + 1:]}'


def _merge_exposition(fams: Dict[str, dict], order: List[str],
                      text: str, replica: str) -> None:
    """Fold one replica's Prometheus text into the family table with the
    ``replica`` label injected into every sample.  Relies on the
    registry's exposition shape (HELP/TYPE immediately precede their
    samples), which both ends of this scrape share."""
    cur: Optional[str] = None
    for line in text.splitlines():
        if line.startswith("# HELP "):
            name, _, help_text = line[len("# HELP "):].partition(" ")
            fam = fams.get(name)
            if fam is None:
                fam = fams[name] = {"help": help_text, "type": "untyped",
                                    "samples": []}
                order.append(name)
            cur = name
        elif line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            fam = fams.get(name)
            if fam is None:
                fam = fams[name] = {"help": "", "type": kind, "samples": []}
                order.append(name)
            else:
                fam["type"] = kind
            cur = name
        elif line and not line.startswith("#"):
            series, _, value = line.rpartition(" ")
            if cur is None:  # exposition without comments: family = name
                cur = series.split("{", 1)[0]
                if cur not in fams:
                    fams[cur] = {"help": "", "type": "untyped",
                                 "samples": []}
                    order.append(cur)
            fams[cur]["samples"].append(
                f"{_inject_replica_label(series, replica)} {value}")


# ---------------------------------------------------------------------------
# supervised subprocess replicas
# ---------------------------------------------------------------------------


class SupervisedReplica:
    """Spawn-and-restart manager for one ``bin/serve.py --lm`` replica
    subprocess.

    The child is started with ``--port 0`` (unless ``port`` pins one)
    and announces its ephemeral port with a ``FDTPU_SERVE_PORT=<n>``
    stdout line — the race-free fleet-orchestration contract.  Its
    remaining stdout is pumped to our stderr (prefixed) so replica logs
    stay visible without deadlocking the pipe.

    :meth:`restart` is shaped as a :class:`Replica` restart hook:
    SIGTERM (the replica's graceful drain finishes in-flight work),
    bounded wait, then respawn — with ``--aot-dir``/``--prewarm`` in
    ``argv`` the successor comes up from the serialized executable pool
    instead of recompiling.
    """

    def __init__(self, argv: Sequence[str], name: str = "replica",
                 env: Optional[dict] = None,
                 startup_timeout: float = 180.0,
                 stop_timeout: float = 45.0,
                 verbose: bool = True):
        self.argv = list(argv)
        self.name = name
        self.env = env
        self.startup_timeout = startup_timeout
        self.stop_timeout = stop_timeout
        #: forward the child's output to our stderr (prefixed).  Tests
        #: pass False: interleaved replica logs corrupt line-oriented
        #: consumers of the parent's output (e.g. pytest progress lines)
        self.verbose = verbose
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None

    def _argv_with_port(self, port: Optional[int]) -> List[str]:
        argv = list(self.argv)
        if "--port" in argv:
            i = argv.index("--port")
            if port is not None:
                argv[i + 1] = str(port)
        else:
            argv += ["--port", "0" if port is None else str(port)]
        return argv

    def spawn(self, port: Optional[int] = None) -> str:
        """Start the child and block until it announces its bound port
        (or dies / times out).  Returns the replica base url."""
        argv = self._argv_with_port(port)
        env = dict(os.environ, **(self.env or {}))
        self.proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, bufsize=1, env=env)
        proc = self.proc
        # a watchdog, not a deadline check between lines: a child that
        # hangs SILENTLY would park readline() forever otherwise
        timer = threading.Timer(
            self.startup_timeout,
            lambda: proc.poll() is None and proc.kill())
        timer.daemon = True
        timer.start()
        sock = None
        assert proc.stdout is not None
        try:
            for line in proc.stdout:
                if self.verbose:
                    sys.stderr.write(f"[{self.name}] {line}")
                if line.startswith("FDTPU_SERVE_PORT="):
                    sock = int(line.split("=", 1)[1].strip())
                    break
        finally:
            timer.cancel()
        if sock is None:
            rc = proc.poll()
            self.stop(sig=signal.SIGKILL)
            raise RouterError(
                f"replica {self.name} "
                + (f"exited (rc={rc})" if rc is not None
                   else f"hung for {self.startup_timeout}s")
                + f" before announcing its port: {' '.join(argv)}")
        self.port = sock
        threading.Thread(target=self._pump, name=f"{self.name}-stdout",
                         daemon=True).start()
        return f"http://127.0.0.1:{self.port}"

    def _pump(self) -> None:
        proc = self.proc
        if proc is None or proc.stdout is None:
            return
        try:
            for line in proc.stdout:
                if self.verbose:
                    sys.stderr.write(f"[{self.name}] {line}")
        except (ValueError, OSError):
            pass  # stream closed at teardown

    def stop(self, sig: int = signal.SIGTERM) -> Optional[int]:
        """Signal the child (SIGTERM = graceful drain) and wait for it,
        escalating to SIGKILL at ``stop_timeout``.  Returns the exit
        code (None if there was no child)."""
        proc = self.proc
        if proc is None:
            return None
        if proc.poll() is None:
            try:
                proc.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass
            try:
                proc.wait(timeout=self.stop_timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        rc = proc.returncode
        if proc.stdout is not None:
            try:
                proc.stdout.close()
            except OSError:
                pass
        self.proc = None
        return rc

    def restart(self, rep: Optional[Replica] = None,
                port: Optional[int] = None) -> str:
        """The :class:`Replica` restart hook: graceful stop, respawn,
        new url."""
        self.stop()
        return self.spawn(port=port)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


def wait_http_ready(url: str, timeout: float = 60.0,
                    poll: float = 0.1) -> dict:
    """Poll ``url`` (a /healthz) until it answers 200, for fleet
    bring-up in scripts/tests.  Returns the body; raises on timeout."""
    deadline = time.monotonic() + timeout
    last = "never reached"
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            last = f"HTTP {e.code}"
        except (OSError, urllib.error.URLError, socket.timeout) as e:
            last = f"{type(e).__name__}: {e}"
        time.sleep(poll)
    raise TimeoutError(f"{url} not ready within {timeout}s ({last})")
