"""Profile-guided pipeline planner: auto-place stage boundaries from
per-block costs.

``pp_1f1b``/``lm_pp`` place stage boundaries by uniform layer count —
fine for a homogeneous decoder stack in isolation, but the real program
is not homogeneous: the embedding runs on pipe device 0 and the
final-norm/logits/loss on device S-1 (both INSIDE the 1F1B schedule,
per microbatch), and a profile may reveal further skew (MoE-free blocks
vs future heterogeneous stacks, measured straggling).  The lockstep
schedule is bottlenecked by its most expensive stage: every tick costs
``max(stage)``, so utilization is ``M·mean/( (M+S-1)·max )`` and any
imbalance is paid on EVERY microbatch, not just in the bubble
(arXiv:2204.10562's planning argument; arXiv:2412.14374 reaches the
same conclusion for MPMD pipelines).

This module closes ROADMAP item 4's loop over the PR-9 data layer
(:mod:`..obs.profile`): consume a cost-profile artifact — or fresh
static costs straight from the staged-out model — and emit a
:class:`PipelinePlan`: non-uniform stage boundaries minimizing the
modeled max-stage cost under an optional per-device memory budget, with
the modeled bubble (planned AND uniform, so the win is auditable) and a
per-stage memory estimate attached.  ``prepare_training(spmd="pp_1f1b",
pp_plan=...)`` and ``bin/driver.py --pp-plan PATH|auto`` execute the
boundaries as static non-uniform ``chunk_stages`` splits (padded to the
max chunk count per device, idle chunks ``lax.cond``-skipped — ONE
compile, the plan never enters a jit signature).

Partitioning is exact, not greedy: a DP over contiguous partitions
minimizing ``(max stage cost, Σ stage_cost²)`` lexicographically — the
secondary term makes flat costs degrade to the uniform split exactly
(same boundaries, same compiled program), so the planner can be left on
everywhere.  Cross-topology reuse of profile-derived plans is rejected
through the same fingerprint recipe as the AOT keys
(:func:`..compilation.topology_fingerprint`).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Optional, Sequence, Tuple

__all__ = [
    "PipelinePlan",
    "PlanError",
    "plan_stages",
    "plan_from_profile",
    "plan_from_model",
    "resolve_plan",
    "stage_costs_for",
    "uniform_boundaries",
]

SCHEMA = "fdtpu-pp-plan/v1"


class PlanError(ValueError):
    """No feasible stage placement for the given costs/budget."""


def uniform_boundaries(depth: int, S: int) -> Tuple[int, ...]:
    """The uniform split's cut points: ``depth`` blocks dealt round-floor
    with the remainder on the leading stages — exactly the layout
    ``obs.profile.stage_costs_from_static`` models and ``lm_pp`` builds
    when ``depth % S == 0``."""
    counts = [depth // S + (1 if i < depth % S else 0) for i in range(S)]
    out = [0]
    for c in counts:
        out.append(out[-1] + c)
    return tuple(out)


def stage_costs_for(block_costs: Sequence[float],
                    boundaries: Sequence[int],
                    outer: Tuple[float, float] = (0.0, 0.0)) -> Tuple[float, ...]:
    """Per-stage cost sums for these cut points, with the outer costs
    folded into the first/last stages (embed runs at logical stage 0,
    head at the last — where the 1F1B schedule executes them)."""
    S = len(boundaries) - 1
    out = []
    for s in range(S):
        c = float(sum(block_costs[boundaries[s]:boundaries[s + 1]]))
        if s == 0:
            c += float(outer[0])
        if s == S - 1:
            c += float(outer[1])
        out.append(c)
    return tuple(out)


@dataclasses.dataclass
class PipelinePlan:
    """Planner output: where to cut the stack, and what the model says
    that placement buys.

    ``boundaries`` — S+1 cut points (``boundaries[s]:boundaries[s+1]``
    is stage s's block range); ``stage_costs`` — modeled per-stage cost
    at those cuts (outer folded in); ``modeled_bubble`` vs
    ``uniform_bubble`` — the schedule model's bubble fraction for the
    planned and the uniform split at ``num_microbatches``;
    ``stage_bytes`` — per-stage memory estimate (stage param bytes plus
    the ``min(S, M)``-slot activation ring when the inputs allowed
    estimating it); ``fingerprint`` — topology digest of the profile
    the costs came from ("" for synthetic/explicit costs).
    """

    boundaries: Tuple[int, ...]
    stage_costs: Tuple[float, ...]
    modeled_bubble: float
    uniform_bubble: float
    num_microbatches: int
    schedule: str = "1f1b"
    stage_bytes: Tuple[float, ...] = ()
    memory_budget: Optional[float] = None
    fingerprint: str = ""
    meta: dict = dataclasses.field(default_factory=dict)
    schema: str = SCHEMA

    def __post_init__(self):
        self.boundaries = tuple(int(b) for b in self.boundaries)
        self.stage_costs = tuple(float(c) for c in self.stage_costs)
        self.stage_bytes = tuple(float(b) for b in self.stage_bytes)

    @property
    def S(self) -> int:
        return len(self.boundaries) - 1

    @property
    def depth(self) -> int:
        return self.boundaries[-1]

    @property
    def counts(self) -> Tuple[int, ...]:
        """Blocks hosted per pipe device."""
        return tuple(self.boundaries[s + 1] - self.boundaries[s]
                     for s in range(self.S))

    @property
    def is_uniform(self) -> bool:
        return self.boundaries == uniform_boundaries(self.depth, self.S)

    # -- persistence (the planner report CI exports + --pp-plan loads) --
    def save(self, path: str) -> str:
        doc = dataclasses.asdict(self)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "PipelinePlan":
        with open(path) as f:
            doc = json.load(f)
        schema = doc.get("schema")
        if schema != SCHEMA:
            raise ValueError(
                f"{path}: not a {SCHEMA} artifact (schema={schema!r}) — "
                "regenerate it with parallel.pp_plan")
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in fields})

    def verify(self, mesh=None, tag: str = "") -> "PipelinePlan":
        """Reject cross-topology reuse: a plan derived from a profile
        measured elsewhere must not drive placement here (same recipe as
        :meth:`..obs.profile.Profile.verify`).  Plans with no recorded
        fingerprint (synthetic/explicit costs) pass — static FLOP ratios
        are topology-free."""
        if not self.fingerprint:
            return self
        from ..compilation import topology_fingerprint
        from ..obs.profile import ProfileMismatch, describe_topology

        current = topology_fingerprint(mesh=mesh, tag=tag)
        if current != self.fingerprint:
            raise ProfileMismatch(
                f"pipeline plan fingerprint {self.fingerprint} does not "
                f"match this process ({current}): the profile it was "
                f"derived from describes different hardware (current "
                f"topology {describe_topology(mesh)}) — re-plan from a "
                "profile collected here")
        return self

    def verify_source_topology(self) -> "PipelinePlan":
        """Re-check the fingerprint against the topology the SOURCE
        profile recorded (``meta.topology_mesh``), rebuilt on this
        process — the consuming run's own mesh may legitimately differ
        (a ``(data, pipe)`` trainer consuming a pipe-only ``pp_bubble``
        profile's plan), but the box must be the one the costs were
        measured on.  A recorded mesh this process cannot rebuild is
        exactly the cross-topology case — rejected with the same error
        type."""
        if not self.fingerprint:
            return self
        shape = (self.meta or {}).get("topology_mesh") or None
        mesh = None
        if shape:
            from ..mesh import make_mesh
            from ..obs.profile import ProfileMismatch

            try:
                mesh = make_mesh({k: int(v) for k, v in shape.items()})
            except ValueError as e:
                raise ProfileMismatch(
                    f"pipeline plan was derived from a profile recorded "
                    f"on mesh {shape}, which this process cannot rebuild "
                    f"({e}) — re-plan from a profile collected here")
        return self.verify(mesh)

    def describe(self) -> str:
        """One-paragraph human summary for driver/bench logs."""
        mx = max(self.stage_costs) if self.stage_costs else 0.0
        mem = (f", peak stage bytes {max(self.stage_bytes):.3e}"
               if self.stage_bytes else "")
        return (f"pp plan: S={self.S} depth={self.depth} "
                f"M={self.num_microbatches} schedule={self.schedule} "
                f"counts={list(self.counts)} max-stage={mx:.3e} "
                f"bubble {self.modeled_bubble:.4f} "
                f"(uniform {self.uniform_bubble:.4f}){mem}")


def _partition(block_costs: Sequence[float], S: int,
               outer: Tuple[float, float],
               feasible) -> Tuple[int, ...]:
    """Exact DP over contiguous partitions of ``depth`` blocks into S
    stages minimizing ``(max stage cost, Σ stage cost²)``
    lexicographically, restricted to ``feasible(s, i, j)`` segments
    (stage s spanning blocks ``i:j``).  Every stage gets >= 1 block.
    Returns boundaries or raises :class:`PlanError`."""
    depth = len(block_costs)
    prefix = [0.0]
    for c in block_costs:
        prefix.append(prefix[-1] + float(c))

    def seg(s: int, i: int, j: int) -> float:
        c = prefix[j] - prefix[i]
        if s == 0:
            c += outer[0]
        if s == S - 1:
            c += outer[1]
        return c

    INF = (math.inf, math.inf)
    # best[s][j] = (max, sumsq) of the best partition of blocks [0, j)
    # into stages 0..s; parent[s][j] = the chosen cut i
    best = [[INF] * (depth + 1) for _ in range(S)]
    parent = [[-1] * (depth + 1) for _ in range(S)]
    for j in range(1, depth - S + 2):
        if feasible(0, 0, j):
            c = seg(0, 0, j)
            best[0][j] = (c, c * c)
            parent[0][j] = 0
    for s in range(1, S):
        # stage s ends at j; at least s blocks behind it, and enough
        # blocks left for the S-1-s stages after it
        for j in range(s + 1, depth - (S - 1 - s) + 1):
            cand = INF
            arg = -1
            # descending i: on full ties (flat costs) the latest cut
            # wins, which reproduces uniform_boundaries' remainder-on-
            # leading-stages layout exactly
            for i in range(j - 1, s - 1, -1):
                prev = best[s - 1][i]
                if math.isinf(prev[0]) or not feasible(s, i, j):
                    continue
                c = seg(s, i, j)
                key = (max(prev[0], c), prev[1] + c * c)
                if key < cand:
                    cand, arg = key, i
            best[s][j] = cand
            parent[s][j] = arg
    if math.isinf(best[S - 1][depth][0]):
        raise PlanError(
            f"no feasible placement of {depth} blocks over {S} stages "
            "under the memory budget — raise the budget, shrink the "
            "model, or add pipe devices")
    bounds = [depth]
    j = depth
    for s in range(S - 1, -1, -1):
        j = parent[s][j]
        bounds.append(j)
    return tuple(reversed(bounds))


def plan_stages(
    block_costs: Sequence[float],
    S: int,
    num_microbatches: int,
    outer: Tuple[float, float] = (0.0, 0.0),
    schedule: str = "1f1b",
    block_bytes: Optional[Sequence[float]] = None,
    outer_bytes: Tuple[float, float] = (0.0, 0.0),
    activation_bytes: float = 0.0,
    memory_budget: Optional[float] = None,
    fingerprint: str = "",
    meta: Optional[dict] = None,
) -> PipelinePlan:
    """Place stage boundaries for ``len(block_costs)`` blocks over S
    pipe devices minimizing the modeled max-stage cost (ties broken
    toward balance, so flat costs return the uniform split exactly).

    ``outer = (embed_cost, head_cost)`` is folded into the first/last
    stages — the reason the planner beats uniform even on a homogeneous
    stack.  ``block_bytes``/``outer_bytes``/``activation_bytes`` feed
    the per-stage memory estimate: stage params plus the 1F1B input
    ring (``min(S, M)`` activation slots per hosted chunk is the
    schedule's stash bound; ``activation_bytes`` is one microbatch
    activation).  ``memory_budget`` (bytes per device) makes
    over-budget segments infeasible instead of merely expensive.
    """
    from ..obs.profile import modeled_bubble

    depth = len(block_costs)
    if S < 1:
        raise PlanError(f"need >= 1 stage, got {S}")
    if depth < S:
        raise PlanError(
            f"{depth} blocks cannot fill {S} pipeline stages (every "
            "stage needs >= 1 block)")
    if num_microbatches < 1:
        raise PlanError(
            f"num_microbatches must be >= 1, got {num_microbatches}")
    if any(c < 0 for c in block_costs):
        raise PlanError("block costs must be non-negative")

    bbytes = [float(b) for b in (block_bytes or [0.0] * depth)]
    if len(bbytes) != depth:
        raise PlanError(
            f"block_bytes has {len(bbytes)} entries for {depth} blocks")
    bprefix = [0.0]
    for b in bbytes:
        bprefix.append(bprefix[-1] + b)
    ring = min(S, num_microbatches)

    def stage_mem(s: int, i: int, j: int) -> float:
        m = bprefix[j] - bprefix[i]
        if s == 0:
            m += outer_bytes[0]
        if s == S - 1:
            m += outer_bytes[1]
        # one input ring per hosted chunk; non-uniform splits execute as
        # V_max padded chunks, but idle chunks stash nothing live
        return m + ring * (j - i) * activation_bytes

    def feasible(s: int, i: int, j: int) -> bool:
        return memory_budget is None or stage_mem(s, i, j) <= memory_budget

    boundaries = _partition(block_costs, S, outer, feasible)
    costs = stage_costs_for(block_costs, boundaries, outer)
    uni = uniform_boundaries(depth, S)
    uni_costs = stage_costs_for(block_costs, uni, outer)
    return PipelinePlan(
        boundaries=boundaries,
        stage_costs=costs,
        modeled_bubble=modeled_bubble(costs, num_microbatches,
                                      schedule=schedule),
        uniform_bubble=modeled_bubble(uni_costs, num_microbatches,
                                      schedule=schedule),
        num_microbatches=num_microbatches,
        schedule=schedule,
        stage_bytes=tuple(
            stage_mem(s, boundaries[s], boundaries[s + 1])
            for s in range(S)),
        memory_budget=memory_budget,
        fingerprint=fingerprint,
        meta=dict(meta or {}),
    )


def plan_from_profile(profile, S: int, num_microbatches: int,
                      schedule: str = "1f1b",
                      memory_budget: Optional[float] = None,
                      activation_bytes: float = 0.0,
                      mesh=None) -> PipelinePlan:
    """Plan from a cost-profile artifact (:class:`..obs.profile.Profile`).

    Uses the artifact's per-block static costs — the explicit
    ``static.model.blocks`` list when a producer recorded per-block
    skew, else the depth-difference ``block`` cost replicated ``depth``
    times — with the outer (embed + head) cost split between the end
    stages.  Call :meth:`Profile.verify` before planning when the
    artifact came from disk; the emitted plan carries the profile's
    fingerprint so consumers re-check at load time.

    The artifact does not record the model width, so the memory
    estimate's activation-ring term must come from the caller:
    ``activation_bytes`` is one microbatch activation (``mb × seqlen ×
    dim × 4``; :func:`resolve_plan` derives it when it has the model).
    Left at 0, a ``memory_budget`` bounds stage PARAM bytes only.
    """
    model_costs = (profile.static or {}).get("model")
    if not model_costs:
        raise PlanError(
            "profile artifact has no static model costs "
            "(static.model is null) — re-collect with a token batch "
            "so lm_layer_costs can stage the model out")
    depth = int(model_costs["depth"])
    blocks = model_costs.get("blocks")
    if blocks:
        block_costs = [float(b["flops"]) for b in blocks]
        block_bytes = [float(b["bytes"]) for b in blocks]
    else:
        block_costs = [float(model_costs["block"]["flops"])] * depth
        block_bytes = [float(model_costs["block"]["bytes"])] * depth
    outer_f = float(model_costs["outer"]["flops"])
    outer_b = float(model_costs["outer"]["bytes"])
    return plan_stages(
        block_costs, S, num_microbatches,
        outer=(outer_f / 2, outer_f / 2),
        schedule=schedule,
        block_bytes=block_bytes,
        outer_bytes=(outer_b / 2, outer_b / 2),
        activation_bytes=activation_bytes,
        memory_budget=memory_budget,
        fingerprint=profile.fingerprint,
        meta={"source": "profile", "batch": model_costs.get("batch"),
              "seqlen": model_costs.get("seqlen"),
              "topology_mesh": (profile.topology or {}).get("mesh")},
    )


def resolve_plan(source: str, S: int, num_microbatches: int,
                 schedule: str = "1f1b", model=None,
                 batch_size: Optional[int] = None,
                 seqlen: Optional[int] = None,
                 memory_budget: Optional[float] = None,
                 verify: bool = True) -> PipelinePlan:
    """Resolve a ``--pp-plan``-style source — the ONE implementation
    behind ``bin/driver.py --pp-plan`` and ``benchmarks/pp_bubble.py
    --plan``, so the two entry points can never drift on what artifacts
    they accept.

    ``source`` is ``"auto"`` (fresh static costs from ``model`` at
    ``batch_size``/``seqlen`` — the full per-data-row batch, which the
    planner divides by M for the activation-ring estimate), a saved
    plan JSON, or a cost-profile artifact (sniffed on the ``schema``
    key).  ``verify=True`` re-checks profile-derived fingerprints
    against this process via :meth:`PipelinePlan.verify_source_topology`
    (raising :class:`..obs.profile.ProfileMismatch` on cross-topology
    reuse); pass ``verify=False`` only for offline analysis of a
    foreign artifact."""
    if source == "auto":
        if model is None or batch_size is None or seqlen is None:
            raise PlanError(
                "resolve_plan('auto') needs model, batch_size and seqlen "
                "to stage fresh static costs")
        plan = plan_from_model(
            model, S, num_microbatches, batch_size=batch_size,
            seqlen=seqlen, schedule=schedule, memory_budget=memory_budget)
    else:
        with open(source) as f:
            doc = json.load(f)
        if doc.get("schema") == SCHEMA:
            plan = PipelinePlan.load(source)
        else:
            from ..obs.profile import Profile

            # the artifact lacks the model width — derive the ring's
            # activation term here when the caller supplied the model,
            # so a memory_budget covers the documented ring bytes
            dim = int(getattr(model, "dim", 0) or 0) if model else 0
            act_bytes = (
                float(max(batch_size // num_microbatches, 1)
                      * seqlen * dim * 4)
                if dim and batch_size and seqlen else 0.0)
            plan = plan_from_profile(
                Profile.load(source), S, num_microbatches,
                schedule=schedule, memory_budget=memory_budget,
                activation_bytes=act_bytes)
        if verify:
            plan.verify_source_topology()
    # fail FAST on a plan that cannot drive this run — a saved plan for
    # a different pipe axis or model must not survive resolution only
    # to crash (after burned sweep/grant time) inside the model wiring
    if plan.S != S:
        raise PlanError(
            f"plan places {plan.S} stages but this run's pipe axis has "
            f"{S} — re-plan for this mesh")
    if model is not None and plan.depth != int(getattr(model, "depth", 0)):
        raise PlanError(
            f"plan partitions {plan.depth} blocks but the model has "
            f"depth {getattr(model, 'depth', 0)} — re-plan for this model")
    return plan


def plan_from_model(model, S: int, num_microbatches: int,
                    batch_size: int, seqlen: int,
                    schedule: str = "1f1b",
                    memory_budget: Optional[float] = None) -> PipelinePlan:
    """Plan from fresh static costs: stage the model out on this process
    (:func:`..obs.profile.lm_layer_costs` — lowering only, nothing
    compiles) and size the memory estimate exactly — per-block param
    bytes from ``eval_shape`` of the real init, one-microbatch
    activation bytes from the model's width.  The ``--pp-plan auto``
    path."""
    import jax
    import jax.numpy as jnp

    from ..obs.profile import lm_layer_costs

    costs = lm_layer_costs(model, batch_size, seqlen)
    if costs is None:
        raise PlanError(
            f"{type(model).__name__} could not be staged out for layer "
            "costs (lm_layer_costs returned None) — pass an explicit "
            "profile artifact instead")
    depth = int(costs["depth"])

    def tree_bytes(tree) -> float:
        return float(sum(
            leaf.size * jnp.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves(tree)))

    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, seqlen), jnp.int32), train=False))
    params = variables["params"]
    block_bytes = [tree_bytes(params[f"block{i}"]) for i in range(depth)]
    outer_bytes = tree_bytes(
        {k: v for k, v in params.items() if not k.startswith("block")})
    mb = max(batch_size // num_microbatches, 1)
    act_bytes = float(mb * seqlen * int(model.dim) * 4)  # f32 ring slots
    return plan_stages(
        [float(costs["block"]["flops"])] * depth, S, num_microbatches,
        outer=(float(costs["outer"]["flops"]) / 2,
               float(costs["outer"]["flops"]) / 2),
        schedule=schedule,
        block_bytes=block_bytes,
        outer_bytes=(outer_bytes / 2, outer_bytes / 2),
        activation_bytes=act_bytes,
        memory_budget=memory_budget,
        meta={"source": "model", "model": type(model).__name__,
              "batch": int(batch_size), "seqlen": int(seqlen)},
    )
