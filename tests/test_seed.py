"""Seed threading into stochastic streams.

The reference has no dropout/drop-path at all (vision CNNs only), so
this is a framework-specific contract: the ``seed`` passed to
``prepare_training``/the step makers must root EVERY stochastic stream —
two seeds draw different masks, the same seed reproduces a run exactly,
and model-selection replicas draw independent masks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# tier-2 (slow): full train-run reproducibility (several trainer runs) — the tier-1 iteration loop must fit the
# 870s verify window (ROADMAP); CI's slow job still runs this file
pytestmark = pytest.mark.slow

import fluxdistributed_tpu as fd
from fluxdistributed_tpu import optim, sharding
from fluxdistributed_tpu.mesh import data_mesh
from fluxdistributed_tpu.models import vit_tiny
from fluxdistributed_tpu.parallel import TrainState, make_train_step
from fluxdistributed_tpu.parallel.dp import flax_loss_fn, make_train_step_shardmap


def _one_step_params(maker, seed):
    """Params after one step of a dropout model, from a fixed init."""
    mesh = data_mesh()
    model = vit_tiny(num_classes=10, dropout=0.5, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (16, 32, 32, 3)).astype(np.float32)
    y = np.asarray(fd.onehot(rng.integers(0, 10, 16), 10))
    variables = model.init(
        {"params": jax.random.PRNGKey(7), "dropout": jax.random.PRNGKey(8)},
        x[:1],
        train=True,
    )
    loss_fn = flax_loss_fn(model, fd.logitcrossentropy)
    opt = optim.momentum(0.1, 0.9)
    step = maker(loss_fn, opt, mesh, donate=False, seed=seed)
    state = TrainState.create(sharding.replicate(variables["params"], mesh), opt)
    batch = sharding.shard_batch({"image": x, "label": y}, mesh)
    state, _ = step(state, batch)
    return jax.tree.map(np.asarray, state.params)


def _max_abs_diff(a, b):
    return max(
        float(np.max(np.abs(x - y)))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def test_same_seed_reproduces_jit():
    a = _one_step_params(make_train_step, seed=3)
    b = _one_step_params(make_train_step, seed=3)
    assert _max_abs_diff(a, b) == 0.0


def test_different_seeds_draw_different_masks_jit():
    a = _one_step_params(make_train_step, seed=3)
    b = _one_step_params(make_train_step, seed=4)
    assert _max_abs_diff(a, b) > 1e-6


def test_different_seeds_draw_different_masks_shardmap():
    a = _one_step_params(make_train_step_shardmap, seed=3)
    b = _one_step_params(make_train_step_shardmap, seed=4)
    assert _max_abs_diff(a, b) > 1e-6


def test_prepare_training_threads_seed():
    """End-to-end: prepare_training(seed=...) reaches the dropout stream."""
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.train import prepare_training, train
    from fluxdistributed_tpu.train.logging import NullLogger

    def run(seed):
        ds = SyntheticDataset(nsamples=32, nclasses=10, shape=(32, 32, 3))
        task = prepare_training(
            vit_tiny(num_classes=10, dropout=0.5, dtype=jnp.float32),
            ds,
            optim.momentum(0.1, 0.9),
            batch_size=16,
            cycles=2,
            seed=seed,
        )
        params, _, _ = train(task, print_every=0, eval_every=0, logger=NullLogger())
        return params

    a, b, c = run(0), run(0), run(1)
    assert _max_abs_diff(a, b) == 0.0  # same seed → bit-identical run
    assert _max_abs_diff(a, c) > 1e-6  # different seed → different run


def test_model_selection_replicas_draw_independent_masks():
    """Identical params + identical data + dropout → per-replica losses
    must still differ, because each replica has its own mask stream."""
    from fluxdistributed_tpu.train.model_selection import prepare_model_selection

    model = vit_tiny(num_classes=10, dropout=0.5, dtype=jnp.float32)
    task = prepare_model_selection(
        model, optim.momentum(0.1, 0.9), input_shape=(32, 32, 3), seed=0
    )
    r = task.replicas
    # collapse to identical replicas so only the mask stream can differ
    params = jax.tree.map(lambda x: jnp.broadcast_to(x[:1], x.shape), task.params)
    opt_state = jax.tree.map(lambda x: jnp.broadcast_to(x[:1], x.shape), task.opt_state)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (1, 4, 32, 32, 3)).astype(np.float32)
    x = jnp.asarray(np.broadcast_to(x, (r, 4, 32, 32, 3)))
    y = np.asarray(fd.onehot(rng.integers(0, 10, 4), 10))
    y = jnp.asarray(np.broadcast_to(y[None], (r, 4, 10)))
    _, _, _, losses = task.step_fn(
        params, opt_state, task.model_state, {"image": x, "label": y},
        jnp.zeros((), jnp.int32), task.dropout_keys,
    )
    losses = np.asarray(losses)
    assert np.unique(losses.round(7)).size > 1, losses
