from .losses import logitcrossentropy, crossentropy, mse
from .metrics import topkaccuracy, onehot, showpreds
from .attention import dot_product_attention, blockwise_attention

__all__ = [
    "logitcrossentropy",
    "crossentropy",
    "mse",
    "topkaccuracy",
    "onehot",
    "showpreds",
    "dot_product_attention",
    "blockwise_attention",
]
