#!/usr/bin/env python
"""Convergence acceptance runs: one learns-not-just-steps check per
model family.

Evidence that the FULL stack learns — dataset parsing → registry →
prefetch loader → compiled DP train step (bf16 on TPU) → compiled eval
— not merely that steps execute.  ``--family`` picks the model family:

* ``cnn`` (default): ResNet-34 on CIFAR-10-format binaries — the
  BASELINE.json "ResNet-34/CIFAR-10 (CPU ref)" config.
* ``vit``: ViT (tiny, patch 4) on the SAME CIFAR-format data, AdamW +
  warmup-cosine — the attention-stack analog of the CNN check.
* ``lm``: transformer LM on an order-1 Markov token stream whose
  conditional entropy is KNOWN (``SyntheticTextDataset``): next-token
  loss must fall from ~ln(vocab) toward the computed entropy floor, a
  quantitative target no memorized-batch test can fake.

This container has no network, so real CIFAR-10 can't be fetched; by
default cnn/vit synthesize a *learnable* dataset in the exact CIFAR
binary layout (1 label byte + 3072 CHW bytes per record: class template
+ noise, 10 classes) and load it through the real ``cifar10`` registry
driver.  Point ``--data`` at a real ``cifar-10-batches-bin`` directory
to run the true dataset; everything downstream is identical.

Prints per-eval {step, loss, val_top1} lines and a final JSON summary.

Usage: python benchmarks/convergence.py [--family cnn|vit|lm]
       [--cycles 300] [--batch 128] [--data DIR] [--platform cpu]
       [--json-out FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def synth_cifar_binaries(root: str, n_train: int = 10000, n_test: int = 2000,
                         seed: int = 0, noise: float = 0.25) -> None:
    """Write a learnable 10-class dataset in the CIFAR-10 binary format."""
    rng = np.random.default_rng(seed)
    templates = rng.normal(0, 1, (10, 32, 32, 3)).astype(np.float32)
    # low-pass the templates so classes are distinguishable after crops
    for _ in range(2):
        templates = (
            templates
            + np.roll(templates, 1, 1) + np.roll(templates, -1, 1)
            + np.roll(templates, 1, 2) + np.roll(templates, -1, 2)
        ) / 5.0

    def write(path: str, n: int):
        labels = rng.integers(0, 10, n).astype(np.uint8)
        x = templates[labels] + rng.normal(0, noise, (n, 32, 32, 3)).astype(np.float32)
        x = (x - x.min()) / (np.ptp(x) + 1e-9)
        imgs = (x * 255).astype(np.uint8).transpose(0, 3, 1, 2)  # HWC→CHW
        rec = np.concatenate(
            [labels[:, None], imgs.reshape(n, 3072)], axis=1
        ).astype(np.uint8)
        rec.tofile(path)

    per = n_train // 5
    for i in range(1, 6):
        write(os.path.join(root, f"data_batch_{i}.bin"), per)
    write(os.path.join(root, "test_batch.bin"), n_test)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", default="cnn", choices=["cnn", "vit", "lm"])
    ap.add_argument("--cycles", type=int, default=300)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--eval-every", type=int, default=50)
    ap.add_argument("--lr", type=float, default=None,
                    help="default: 0.05 (cnn, momentum), 3e-3 (vit, adamw), "
                         "3e-3 (lm, adam)")
    ap.add_argument("--data", default=None, help="real cifar-10-batches-bin dir")
    ap.add_argument("--vocab", type=int, default=64, help="lm family")
    ap.add_argument("--seqlen", type=int, default=64, help="lm family")
    ap.add_argument("--peak", type=float, default=0.9,
                    help="lm family: Markov-chain peak transition prob")
    ap.add_argument("--platform", default=None)
    ap.add_argument("--json-out", default=None)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import shutil

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    if args.family == "lm":
        run_lm(args)
        return

    if args.data:
        root = args.data
        synthetic = False
    else:
        root = tempfile.mkdtemp(prefix="cifar_synth_")
        synth_cifar_binaries(root)
        synthetic = True

    try:
        run(args, root, synthetic)
    finally:
        if synthetic:
            shutil.rmtree(root, ignore_errors=True)


def _recorder(history):
    from fluxdistributed_tpu.train.logging import Logger

    class Recorder(Logger):
        def log(self, metrics: dict, step=None):
            row = {"step": int(step or 0),
                   **{k: float(v) for k, v in metrics.items()}}
            history.append(row)
            if any(k.startswith("val") for k in metrics) or "train_step_loss" in metrics:
                print(json.dumps(row), flush=True)

        def info(self, msg: str):
            print(msg, flush=True)

    return Recorder()


def run(args, root: str, synthetic: bool):
    import jax

    from fluxdistributed_tpu import optim
    from fluxdistributed_tpu.data.registry import open_dataset, register_dataset
    from fluxdistributed_tpu.models import resnet34, vit_tiny
    from fluxdistributed_tpu.train import prepare_training, train

    register_dataset("cifar_conv", "cifar10", path=root, split="train")
    register_dataset("cifar_conv_val", "cifar10", path=root, split="test")
    ds = open_dataset("cifar_conv")
    val = open_dataset("cifar_conv_val")

    if args.family == "vit":
        model = vit_tiny(num_classes=10)
        lr = args.lr if args.lr is not None else 3e-3
        opt = optim.adamw(
            optim.warmup_cosine(lr, min(50, args.cycles // 5), args.cycles)
        )
        metric = "ViT-tiny/CIFAR-10-format convergence"
    else:
        model = resnet34(num_classes=10)
        lr = args.lr if args.lr is not None else 0.05
        opt = optim.momentum(
            optim.warmup_cosine(lr, min(50, args.cycles // 5), args.cycles), 0.9
        )
        metric = "ResNet-34/CIFAR-10-format convergence"

    history: list[dict] = []
    task = prepare_training(
        model,
        ds,
        opt,
        batch_size=args.batch,
        cycles=args.cycles,
        val_dataset=val,
        val_samples=512,
        seed=args.seed,
        topk=(1, 5),
        input_shape=(32, 32, 3),
    )
    rec = _recorder(history)
    train(
        task,
        print_every=max(args.cycles // 10, 1),
        eval_every=args.eval_every,
        topk=(1, 5),
        logger=rec,
    )
    # final eval on the FINISHED model — the in-loop cadence can be up to
    # eval_every-1 steps stale relative to the returned weights
    from fluxdistributed_tpu.train.trainer import _eval_and_log

    _eval_and_log(task, task.val_batch, "val", args.cycles, (1, 5), rec)

    evals = [h for h in history if "val_top1" in h]
    summary = {
        "metric": metric,
        "dataset": "synthetic-cifar-binary" if synthetic else "cifar10",
        "cycles": args.cycles,
        "global_batch": args.batch,
        "first_val_top1": evals[0]["val_top1"] if evals else None,
        "final_val_top1": evals[-1]["val_top1"] if evals else None,
        "final_val_loss": evals[-1]["val_loss"] if evals else None,
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(summary))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump({"summary": summary, "history": history}, f, indent=1)


def run_lm(args):
    """LM acceptance: next-token loss must approach the KNOWN entropy
    floor of the Markov chain generating the stream."""
    import jax

    from fluxdistributed_tpu import optim
    from fluxdistributed_tpu.data import SyntheticTextDataset
    from fluxdistributed_tpu.models import lm_loss_fn, lm_tiny
    from fluxdistributed_tpu.train import prepare_training, train
    from fluxdistributed_tpu.train.trainer import _eval_and_log

    v, peak = args.vocab, args.peak
    if not (1.0 / v) < peak < 1.0:
        raise SystemExit(
            f"--peak must be in (1/vocab, 1) for a meaningful entropy floor "
            f"(got {peak} with vocab {v})")
    # conditional entropy of the order-1 chain (nats/token) — EXACT for
    # the loss: next_token_loss scores only tokens[:, 1:], all of which
    # are pure Markov transitions (the uniform first token is never a
    # prediction target)
    floor = -(peak * np.log(peak) + (1 - peak) * np.log((1 - peak) / (v - 1)))

    ds = SyntheticTextDataset(vocab=v, seqlen=args.seqlen,
                              seed=args.seed, peak=peak)
    model = lm_tiny(vocab=v)
    lr = args.lr if args.lr is not None else 3e-3
    history: list[dict] = []
    task = prepare_training(
        model,
        ds,
        optim.adam(optim.warmup_cosine(lr, min(50, args.cycles // 5), args.cycles)),
        batch_size=args.batch,
        cycles=args.cycles,
        loss_fn=lm_loss_fn(model),
        topk=(),
        val_dataset=ds,
        val_samples=max(args.batch, 64),
        seed=args.seed,
    )
    rec = _recorder(history)
    train(
        task,
        print_every=max(args.cycles // 10, 1),
        eval_every=args.eval_every,
        topk=(),
        logger=rec,
    )
    _eval_and_log(task, task.val_batch, "val", args.cycles, (), rec)

    evals = [h for h in history if "val_loss" in h]
    first = evals[0]["val_loss"] if evals else None
    final = evals[-1]["val_loss"] if evals else None
    summary = {
        "metric": "lm_tiny/Markov-stream convergence",
        "dataset": f"markov(vocab={v}, peak={peak})",
        "cycles": args.cycles,
        "global_batch": args.batch,
        "uniform_loss": round(float(np.log(v)), 4),
        "entropy_floor": round(float(floor), 4),
        "first_val_loss": first,
        "final_val_loss": final,
        # 1.0 = reached the floor, 0.0 = no better than uniform
        "fraction_of_gap_closed": (
            round((np.log(v) - final) / (np.log(v) - floor), 4)
            if final is not None else None
        ),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(summary))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump({"summary": summary, "history": history}, f, indent=1)


if __name__ == "__main__":
    main()
