"""Layer-1 AST rules: JAX-specific hazards detectable from source alone.

Every rule targets a bug class this repo has actually hit or structurally
risks (see ISSUE 5 / docs/analysis.md for the catalog):

========  ======================================================
FDT101    Python ``if``/``while`` on a probable tracer inside a
          jit-reachable function (TracerBoolConversionError at best,
          silently trace-time-frozen control flow at worst)
FDT102    wall-clock / host RNG / host I/O inside a jitted or
          span-bracketed hot path (baked into the trace as a constant,
          or corrupting interval math on clock jumps)
FDT103    ``jnp.array(<python scalar>)`` without ``dtype=`` — weak-type
          promotion traps that retrigger compilation when mixed
FDT104    jit-reachable closure reading a MUTABLE module global (the
          trace captures one snapshot; later mutation silently ignored)
FDT105    mesh-axis name literals not sourced from ``mesh.py``'s
          declarations (unknown literal = error; a hardcoded copy of a
          declared axis = warning — renames drift silently)
FDT106    metric names off the byte-pinned ``fdtpu_*`` convention
          (obs/ parity tests pin the exposition byte-for-byte)
FDT107    a train-step factory whose docstring documents donation but
          whose ``jax.jit`` calls never pass ``donate_argnums``
========  ======================================================

The engine is deliberately stdlib-``ast`` only: rules run anywhere (CI,
pre-commit, the bench harness) without importing jax, in milliseconds.
Detection is heuristic by design — the jit-reachability walk is a
module-local name-based call graph, not an import-following analyzer —
so rules err toward *precision* (static-by-convention accesses like
``x.shape`` / ``isinstance(x, ...)`` are excluded) and anything
reviewed-and-accepted goes in the baseline rather than growing a
suppression syntax.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set

from .findings import Finding

__all__ = [
    "AstRule",
    "AST_RULES",
    "ModuleContext",
    "ast_rule",
    "declared_mesh_axes",
    "run_ast_rules",
]

#: wrapper callables whose argument (or decorated function) is traced —
#: reachability roots.  ``shard_map`` bodies are traced exactly like jit
#: bodies, so the same hazards apply.
_TRACE_WRAPPERS = ("jit", "shard_map", "eval_shape", "vmap", "grad",
                  "value_and_grad", "checkpoint", "remat", "scan",
                  "while_loop", "fori_loop", "pmap")

#: attribute accesses on a tracer that are static at trace time — a
#: branch on these is ordinary Python, not a tracer branch
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                 "is_deleted", "weak_type"}

#: builtins whose result on a tracer is static (len → a dim, isinstance
#: → a type test, ...)
_STATIC_CALLS = {"isinstance", "hasattr", "callable", "len", "getattr",
                 "type", "issubclass"}

#: dotted host-side calls that must not appear in traced code: their
#: value is captured ONCE at trace time and baked into the program
_HOST_CALLS_IN_JIT = re.compile(
    r"^(time\.(time|perf_counter|monotonic|sleep)"
    r"|(np|numpy)\.random\.\w+"
    r"|random\.(random|randint|uniform|choice|seed|gauss|shuffle)"
    r"|open|input)$")

#: the serving/training metric-name convention, byte-pinned by obs/
#: parity tests — see obs/metrics.py
_METRIC_NAME_RE = re.compile(r"^fdtpu_[a-z0-9_]+$")


def _const_ints(node: ast.AST) -> List[int]:
    vals = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    return [v.value for v in vals
            if isinstance(v, ast.Constant) and isinstance(v.value, int)]


def _const_strs(node: ast.AST) -> List[str]:
    vals = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    return [v.value for v in vals
            if isinstance(v, ast.Constant) and isinstance(v.value, str)]


def _dotted(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_trace_wrapper(node: ast.AST) -> bool:
    """Does ``node`` name a tracing wrapper (``jax.jit``, bare ``jit``,
    ``jax.experimental.shard_map.shard_map``, ``lax.scan``, ...)?"""
    d = _dotted(node)
    return bool(d) and d.split(".")[-1] in _TRACE_WRAPPERS


@dataclasses.dataclass
class _FuncInfo:
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    qualname: str
    params: Set[str]
    param_order: List[str]  # positional params, for static_argnums
    parent: Optional["_FuncInfo"]


class ModuleContext:
    """One parsed module + the derived facts rules share: the function
    index, the jit-reachable set, and the mesh-axis declarations."""

    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.Module, axes: Optional[Set[str]] = None):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.axes = axes if axes is not None else declared_mesh_axes()
        self.functions: List[_FuncInfo] = []
        self._by_name: Dict[str, List[_FuncInfo]] = {}
        self._index_functions()
        #: per entry-function name: the static_argnums/static_argnames
        #: its wrapper call declares (those params are NOT tracers)
        self.entry_static: Dict[str, Dict[str, tuple]] = {}
        entries = self._entry_names()
        self.jit_entries: Set[int] = {
            id(f.node) for f in self.functions
            if f.node.name in entries}
        self.jit_reachable: Set[int] = self._jit_reachable(entries)

    # -- function index ----------------------------------------------------

    def _index_functions(self) -> None:
        ctx = self

        class V(ast.NodeVisitor):
            def __init__(self):
                self.stack: List[_FuncInfo] = []

            def _visit_func(self, node):
                order = [a.arg for a in
                         (node.args.posonlyargs + node.args.args)
                         if a.arg not in ("self", "cls")]
                params = set(order) | {
                    a.arg for a in node.args.kwonlyargs
                } | {a.arg for a in (node.args.vararg, node.args.kwarg) if a}
                qual = ".".join([f.node.name for f in self.stack] + [node.name])
                info = _FuncInfo(node, qual, params, order,
                                 self.stack[-1] if self.stack else None)
                ctx.functions.append(info)
                ctx._by_name.setdefault(node.name, []).append(info)
                self.stack.append(info)
                self.generic_visit(node)
                self.stack.pop()

            visit_FunctionDef = _visit_func
            visit_AsyncFunctionDef = _visit_func

        V().visit(self.tree)

    def own_nodes(self, info: _FuncInfo) -> Iterable[ast.AST]:
        """Nodes of a function's immediate body, not descending into
        nested function definitions (those are their own _FuncInfo)."""
        stack: List[ast.AST] = list(ast.iter_child_nodes(info.node))
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(n))

    # -- jit reachability --------------------------------------------------

    def _entry_names(self) -> Set[str]:
        """Function names handed to a tracing wrapper anywhere in the
        module: ``jax.jit(step)``, ``@jax.jit``, ``@partial(jax.jit,
        ...)``, ``jax.jit(self._step_impl, ...)``."""
        names: Set[str] = set()

        def record_static(name: str, call: Optional[ast.Call]) -> None:
            info = self.entry_static.setdefault(
                name, {"argnums": (), "argnames": ()})
            if call is None:
                return
            for k in call.keywords:
                if k.arg == "static_argnums":
                    info["argnums"] = tuple(_const_ints(k.value))
                elif k.arg == "static_argnames":
                    info["argnames"] = tuple(_const_strs(k.value))

        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    if _is_trace_wrapper(target):
                        names.add(node.name)
                        record_static(
                            node.name,
                            dec if isinstance(dec, ast.Call) else None)
                    elif (isinstance(dec, ast.Call)
                          and _dotted(dec.func).split(".")[-1] == "partial"
                          and dec.args and _is_trace_wrapper(dec.args[0])):
                        names.add(node.name)
                        record_static(node.name, dec)
            elif isinstance(node, ast.Call) and _is_trace_wrapper(node.func):
                # ALL positional name args, not just the first: the
                # traced callable's position varies (``fori_loop(0, n,
                # body, x)``, ``while_loop(cond, body, x)`` traces two)
                for arg in node.args:
                    d = _dotted(arg)
                    if d:
                        names.add(d.split(".")[-1])
                        record_static(d.split(".")[-1], node)
        return names

    def _jit_reachable(self, entries: Set[str]) -> Set[int]:
        """ids of _FuncInfo nodes traced under some wrapper: the entry
        functions plus everything they reference by name (called OR
        passed as a callback — ``lax.scan(body, ...)``,
        ``tree_map(leaf, ...)`` and ``value_and_grad(lossf)`` all trace
        their argument)."""
        reachable: Set[int] = set()
        work: List[_FuncInfo] = []
        for info in self.functions:
            if info.node.name in entries:
                work.append(info)
        while work:
            info = work.pop()
            if id(info.node) in reachable:
                continue
            reachable.add(id(info.node))
            for n in self.own_nodes(info):
                d = _dotted(n) if isinstance(n, (ast.Name, ast.Attribute)) else ""
                if not d:
                    continue
                leaf = d.split(".")[-1]
                for cand in self._by_name.get(leaf, []):
                    if id(cand.node) not in reachable:
                        work.append(cand)
        return reachable

    def jit_functions(self) -> List[_FuncInfo]:
        return [f for f in self.functions if id(f.node) in self.jit_reachable]


# -- mesh axis declarations ----------------------------------------------

_AXES_CACHE: Optional[Set[str]] = None


def declared_mesh_axes(mesh_path: Optional[str] = None) -> Set[str]:
    """The axis-name literals declared as ``*_AXIS = "..."`` in
    ``mesh.py`` — THE source of truth every other axis mention must
    derive from.  Parsed from source (not imported) so the linter works
    without jax on the path."""
    global _AXES_CACHE
    if mesh_path is None and _AXES_CACHE is not None:
        return _AXES_CACHE
    import os

    path = mesh_path or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "mesh.py")
    axes: Set[str] = set()
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError, ValueError):
        # axes UNKNOWN (mesh.py unreadable/mid-edit) — FDT105 must then
        # stand down entirely rather than call every literal undeclared;
        # the empty set signals that (and FDT000 reports the parse error)
        return set()
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.endswith("_AXIS")
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            axes.add(node.value.value)
    if mesh_path is None:
        _AXES_CACHE = axes
    return axes


# -- rule registry --------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AstRule:
    id: str
    name: str
    severity: str
    description: str
    hint: str
    check: Callable[[ModuleContext], Iterable[Finding]]


AST_RULES: List[AstRule] = []


def ast_rule(id: str, name: str, severity: str, description: str, hint: str):
    """Register an AST rule.  ``check(ctx)`` yields findings; the
    decorator fills rule id / severity / hint so rule bodies only state
    locations and messages."""

    def deco(fn):
        rule = AstRule(id, name, severity, description, hint, fn)
        AST_RULES.append(rule)
        return fn

    return deco


def _finding(rule: AstRule, ctx: ModuleContext, node: ast.AST,
             message: str, detail: str, severity: Optional[str] = None,
             hint: Optional[str] = None) -> Finding:
    return Finding(
        rule=rule.id,
        severity=severity or rule.severity,
        file=ctx.relpath,
        line=getattr(node, "lineno", 0),
        message=message,
        hint=hint if hint is not None else rule.hint,
        detail=detail,
    )


def _rule_by_id(rid: str) -> AstRule:
    return next(r for r in AST_RULES if r.id == rid)


def run_ast_rules(ctx: ModuleContext,
                  rules: Optional[Sequence[AstRule]] = None) -> List[Finding]:
    out: List[Finding] = []
    for rule in (rules or AST_RULES):
        out.extend(rule.check(ctx))
    return out


# -- FDT101: tracer branch -------------------------------------------------

def _dynamic_param_uses(test: ast.AST, params: Set[str]) -> List[ast.Name]:
    """Name nodes in ``test`` that reference a traced parameter in a way
    that needs its VALUE (not static metadata like ``.shape``)."""
    hits: List[ast.Name] = []

    def walk(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return  # x.shape / x.dtype — static at trace time
        if (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id in _STATIC_CALLS):
            return  # isinstance(x, ...) / len(x) — static
        if (isinstance(n, ast.Compare)
                and all(isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops)):
            return  # x is None — identity, not value
        if isinstance(n, ast.Name) and n.id in params:
            hits.append(n)
        for c in ast.iter_child_nodes(n):
            walk(c)

    walk(test)
    return hits


@ast_rule(
    "FDT101", "tracer-branch", "warning",
    "Python `if`/`while` on a probable tracer inside a jit-reachable "
    "function — control flow freezes at trace time (or raises "
    "TracerBoolConversionError).",
    "use jnp.where / lax.cond / lax.while_loop, or hoist the branch out "
    "of the traced function (closure constants branch fine)")
def _check_tracer_branch(ctx: ModuleContext) -> Iterable[Finding]:
    rule = _rule_by_id("FDT101")
    # entry functions ONLY: a direct jit/shard_map target's parameters
    # are tracers by construction (minus declared static args), while
    # helpers reached transitively often take static config params —
    # flagging those would drown the signal
    for info in ctx.functions:
        if id(info.node) not in ctx.jit_entries:
            continue
        static = ctx.entry_static.get(info.node.name,
                                      {"argnums": (), "argnames": ()})
        params = set(info.params) - set(static["argnames"])
        for i in static["argnums"]:
            if 0 <= i < len(info.param_order):
                params.discard(info.param_order[i])
        for n in ctx.own_nodes(info):
            if not isinstance(n, (ast.If, ast.While)):
                continue
            for name in _dynamic_param_uses(n.test, params):
                kind = "while" if isinstance(n, ast.While) else "if"
                yield _finding(
                    rule, ctx, n,
                    f"`{kind} ...{name.id}...` branches on parameter "
                    f"{name.id!r} of traced function {info.qualname}()",
                    detail=f"{info.qualname}:{name.id}")
                break  # one finding per statement


# -- FDT102: host calls in hot paths --------------------------------------

def _span_bracketed(ctx: ModuleContext, info: _FuncInfo) -> bool:
    """Does this function open obs-style phase/span brackets (`with
    phases(...)` / `with tracer.span(...)`)?  Such functions are hot
    paths by declaration — their timing math must be monotonic."""
    for n in ctx.own_nodes(info):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                e = item.context_expr
                if isinstance(e, ast.Call):
                    d = _dotted(e.func)
                    if d.split(".")[-1] in ("span", "phases"):
                        return True
    return False


@ast_rule(
    "FDT102", "host-call-in-hot-path", "warning",
    "wall-clock / host RNG / host I/O inside a jitted function (baked "
    "into the trace as a constant) or `time.time()` inside a "
    "span-bracketed hot path (wall clock jumps corrupt interval math).",
    "in traced code: jax.random / jax.debug.print / pass values as "
    "arguments; in span-bracketed host loops: time.perf_counter()")
def _check_host_calls(ctx: ModuleContext) -> Iterable[Finding]:
    rule = _rule_by_id("FDT102")
    jit_ids = ctx.jit_reachable
    for info in ctx.functions:
        in_jit = id(info.node) in jit_ids
        spanned = False if in_jit else _span_bracketed(ctx, info)
        if not (in_jit or spanned):
            continue
        for n in ctx.own_nodes(info):
            if not isinstance(n, ast.Call):
                continue
            d = _dotted(n.func)
            if in_jit and _HOST_CALLS_IN_JIT.match(d):
                yield _finding(
                    rule, ctx, n,
                    f"host call {d}() inside traced function "
                    f"{info.qualname}() — evaluated ONCE at trace time, "
                    "then a constant in every execution",
                    detail=f"{info.qualname}:{d}")
            elif spanned and d == "time.time":
                yield _finding(
                    rule, ctx, n,
                    f"time.time() in span-bracketed hot path "
                    f"{info.qualname}() — wall clock is not monotonic; "
                    "NTP steps/DST corrupt rates and span math",
                    detail=f"{info.qualname}:time.time")


# -- FDT103: weak-typed scalar --------------------------------------------

def _is_scalar_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool):
        return True
    return (isinstance(node, ast.UnaryOp)
            and isinstance(node.op, (ast.USub, ast.UAdd))
            and _is_scalar_literal(node.operand))


@ast_rule(
    "FDT103", "weak-scalar", "warning",
    "`jnp.array(<python scalar>)` without dtype= in traced code — the "
    "weak-typed result changes promotion, and at jit boundaries a "
    "scalar-vs-array dtype flip retriggers compilation.",
    "pin it: jnp.array(x, dtype=jnp.float32) (or jnp.int32), or use "
    "jnp.zeros/ones with an explicit dtype")
def _check_weak_scalar(ctx: ModuleContext) -> Iterable[Finding]:
    rule = _rule_by_id("FDT103")
    for info in ctx.jit_functions():
        for n in ctx.own_nodes(info):
            if not isinstance(n, ast.Call):
                continue
            d = _dotted(n.func)
            if d.split(".")[-1] not in ("array", "asarray") or \
                    not d.startswith(("jnp.", "jax.numpy.")):
                continue
            if not n.args or not _is_scalar_literal(n.args[0]):
                continue
            has_dtype = len(n.args) >= 2 or any(
                k.arg == "dtype" for k in n.keywords)
            if not has_dtype:
                yield _finding(
                    rule, ctx, n,
                    f"{d}({ast.unparse(n.args[0])}) without dtype= in "
                    f"traced function {info.qualname}() is weak-typed",
                    detail=f"{info.qualname}:{ast.unparse(n.args[0])}")


# -- FDT104: mutable global captured by a traced closure ------------------

def _mutable_globals(ctx: ModuleContext) -> Set[str]:
    muts: Set[str] = set()
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v = node.value
            if isinstance(v, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)):
                muts.add(node.targets[0].id)
            elif isinstance(v, ast.Call) and _dotted(v.func) in (
                    "list", "dict", "set", "collections.defaultdict",
                    "collections.OrderedDict"):
                muts.add(node.targets[0].id)
    # anything rebound via `global NAME` is mutable by definition
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Global):
            muts.update(node.names)
    return muts


@ast_rule(
    "FDT104", "mutable-global-in-jit", "warning",
    "a traced function reads a mutable module global — the trace "
    "captures ONE snapshot; later mutation is silently ignored by "
    "every compiled execution.",
    "pass the value as an argument (retraces on change) or make the "
    "global an immutable constant (tuple / frozen dataclass)")
def _check_mutable_global(ctx: ModuleContext) -> Iterable[Finding]:
    rule = _rule_by_id("FDT104")
    muts = _mutable_globals(ctx)
    if not muts:
        return
    for info in ctx.jit_functions():
        locals_: Set[str] = set(info.params)
        for n in ctx.own_nodes(info):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                locals_.add(n.id)
        seen: Set[str] = set()
        for n in ctx.own_nodes(info):
            if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                    and n.id in muts and n.id not in locals_
                    and n.id not in seen):
                seen.add(n.id)
                yield _finding(
                    rule, ctx, n,
                    f"traced function {info.qualname}() reads mutable "
                    f"module global {n.id!r}",
                    detail=f"{info.qualname}:{n.id}")


# -- FDT105: axis-name literals -------------------------------------------

def _axis_literal_findings(ctx: ModuleContext, rule: AstRule):
    if ctx.relpath.replace("\\", "/").endswith("fluxdistributed_tpu/mesh.py"):
        return  # the declarations themselves
    axes = ctx.axes
    if not axes:
        return  # axes unknown (mesh.py unparseable) is not axes invalid
    func_stack: List[str] = []

    def fname() -> str:
        return func_stack[-1] if func_stack else "<module>"

    def walk(node: ast.AST):
        is_func = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        if is_func:
            func_stack.append(node.name)
            # (c) parameter defaults for *axis* parameters
            a = node.args
            pos = a.posonlyargs + a.args
            for arg, default in zip(pos[len(pos) - len(a.defaults):],
                                    a.defaults):
                if arg.arg.endswith("axis") and isinstance(default, ast.Constant) \
                        and isinstance(default.value, str) and default.value in axes:
                    yield _finding(
                        rule, ctx, default,
                        f"default {arg.arg}={default.value!r} hardcodes a "
                        "mesh axis name",
                        detail=f"{node.name}:{arg.arg}={default.value}",
                        severity="warning",
                        hint="default it to the mesh constant "
                             "(mesh.DATA_AXIS / MODEL_AXIS / ...) so a "
                             "rename cannot drift")
            for kwarg, default in zip(a.kwonlyargs, a.kw_defaults):
                if default is not None and kwarg.arg.endswith("axis") \
                        and isinstance(default, ast.Constant) \
                        and isinstance(default.value, str) and default.value in axes:
                    yield _finding(
                        rule, ctx, default,
                        f"default {kwarg.arg}={default.value!r} hardcodes a "
                        "mesh axis name",
                        detail=f"{node.name}:{kwarg.arg}={default.value}",
                        severity="warning",
                        hint="default it to the mesh constant so a rename "
                             "cannot drift")
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d.split(".")[-1] == "ShardLargest":
                # (e) shape-driven rule values in declarative rule
                # tables (parallel/rules.py): the axis argument is a
                # mesh axis name exactly like a P() entry
                cands = list(node.args[:1]) + [
                    kw.value for kw in node.keywords if kw.arg == "axis"]
                for e in cands:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        if e.value not in axes:
                            yield _finding(
                                rule, ctx, e,
                                f"ShardLargest axis {e.value!r} is not "
                                "declared in mesh.py — rule resolution "
                                "rejects it on any real mesh",
                                detail=f"{fname()}:ShardLargest:{e.value}")
                        else:
                            yield _finding(
                                rule, ctx, e,
                                f"ShardLargest hardcodes axis "
                                f"{e.value!r} as a string literal",
                                detail=f"{fname()}:ShardLargest:{e.value}",
                                severity="warning",
                                hint="use the mesh constant "
                                     "(mesh.FSDP_AXIS / ...) instead "
                                     "of the literal")
            if d.split(".")[-1] in ("P", "PartitionSpec"):
                # (a) P()/PartitionSpec() arguments, including tuples
                for arg in node.args:
                    elts = arg.elts if isinstance(arg, ast.Tuple) else [arg]
                    for e in elts:
                        if isinstance(e, ast.Constant) and isinstance(e.value, str):
                            if e.value not in axes:
                                yield _finding(
                                    rule, ctx, e,
                                    f"PartitionSpec axis {e.value!r} is not "
                                    "declared in mesh.py — GSPMD will "
                                    "reject it at compile time on any "
                                    "real mesh",
                                    detail=f"{fname()}:P:{e.value}")
                            else:
                                yield _finding(
                                    rule, ctx, e,
                                    f"PartitionSpec hardcodes axis "
                                    f"{e.value!r} as a string literal",
                                    detail=f"{fname()}:P:{e.value}",
                                    severity="warning",
                                    hint="use the mesh constant "
                                         "(mesh.DATA_AXIS / ...) instead "
                                         "of the literal")
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.endswith("_AXIS") \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str) \
                and node.value.value in axes:
            # (b) a duplicate declaration of a mesh.py axis
            yield _finding(
                rule, ctx, node,
                f"{node.targets[0].id} = {node.value.value!r} re-declares "
                "a mesh.py axis literal — renames drift silently",
                detail=f"{fname()}:{node.targets[0].id}",
                severity="warning",
                hint="import the constant from fluxdistributed_tpu.mesh "
                     "instead of re-declaring the literal")
        if isinstance(node, ast.Subscript) \
                and isinstance(node.value, ast.Attribute) \
                and node.value.attr == "shape" \
                and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str) \
                and node.slice.value in axes:
            # (d) mesh.shape["pipe"]-style lookups
            yield _finding(
                rule, ctx, node,
                f".shape[{node.slice.value!r}] hardcodes a mesh axis name",
                detail=f"{fname()}:shape:{node.slice.value}",
                severity="warning",
                hint="index with the mesh constant (mesh.PIPE_AXIS / ...)")
        for child in ast.iter_child_nodes(node):
            yield from walk(child)
        if is_func:
            func_stack.pop()

    yield from walk(ctx.tree)


@ast_rule(
    "FDT105", "axis-literal", "error",
    "mesh-axis name literals not sourced from mesh.py's declarations — "
    "in PartitionSpecs, axis-parameter defaults, mesh.shape lookups AND "
    "declarative rule tables (ShardLargest axis arguments): an unknown "
    "literal fails GSPMD partitioning at compile time; a hardcoded copy "
    "of a declared axis drifts silently on rename.",
    "source axis names from fluxdistributed_tpu.mesh constants")
def _check_axis_literal(ctx: ModuleContext) -> Iterable[Finding]:
    yield from _axis_literal_findings(ctx, _rule_by_id("FDT105"))


# -- FDT106: metric-name convention ---------------------------------------

def _str_bindings(tree: ast.Module) -> Dict[str, str]:
    """Names that resolve to exactly ONE compile-time string across the
    whole module — the registration-prefix idiom (``METRIC_PREFIX =
    "fdtpu_serve_"``; ``r, p = self.registry, METRIC_PREFIX``) that
    FDT106 must see through.  Conservative on purpose: a name that is
    ever a function parameter, a loop target, or assigned anything
    unresolvable never resolves (a false "covered" is worse than a
    skipped dynamic name)."""
    raw: Dict[str, list] = {}

    def poison(target) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                raw.setdefault(n.id, []).append(None)

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            pairs = []
            for t in node.targets:
                if isinstance(t, ast.Tuple) and isinstance(
                        node.value, ast.Tuple) and len(t.elts) == len(
                        node.value.elts):
                    pairs.extend(zip(t.elts, node.value.elts))
                else:
                    pairs.append((t, node.value))
            for t, v in pairs:
                if isinstance(t, ast.Name):
                    raw.setdefault(t.id, []).append(v)
                else:
                    poison(t)
        elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name):
            raw.setdefault(node.target.id, []).append(node.value)
        elif isinstance(node, ast.AugAssign):
            # PREFIX += "..." rebinds to a value this resolver does not
            # model — the stale original must not keep resolving
            poison(node.target)
        elif isinstance(node, ast.NamedExpr):
            poison(node.target)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            poison(node.target)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    poison(item.optional_vars)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            raw.setdefault(node.name, []).append(None)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                raw.setdefault(alias.asname or alias.name.split(".")[0],
                               []).append(None)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            a = node.args
            for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs,
                        *((a.vararg,) if a.vararg else ()),
                        *((a.kwarg,) if a.kwarg else ())):
                raw.setdefault(arg.arg, []).append(None)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                poison(gen.target)
    resolved: Dict[str, str] = {}
    for _ in range(4):  # fixpoint: aliases of aliases settle in passes
        changed = False
        for name, vals in raw.items():
            if name in resolved:
                continue
            out = set()
            for v in vals:
                s = _const_str(v, resolved) if v is not None else None
                if s is None:
                    out = None
                    break
                out.add(s)
            if out and len(out) == 1:
                resolved[name] = out.pop()
                changed = True
        if not changed:
            break
    return resolved


def _const_str(node, bindings: Dict[str, str]) -> Optional[str]:
    """Resolve an expression to a compile-time string (literal, resolved
    name, ``+`` concatenation, f-string of resolvable parts) or None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return bindings.get(node.id)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _const_str(node.left, bindings)
        right = _const_str(node.right, bindings)
        return left + right if left is not None and right is not None else None
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif isinstance(v, ast.FormattedValue):
                if v.format_spec is not None or v.conversion != -1:
                    return None
                s = _const_str(v.value, bindings)
                if s is None:
                    return None
                parts.append(s)
            else:
                return None
        return "".join(parts)
    return None


@ast_rule(
    "FDT106", "metric-name", "warning",
    "a metric registered off the byte-pinned `fdtpu_*` snake_case "
    "convention — dashboards and the obs/ exposition parity tests key "
    "on the prefix.  Prefix-constant concatenations (`METRIC_PREFIX + "
    "\"queue_depth\"`) are resolved; truly dynamic names stay out of "
    "scope.",
    "name it fdtpu_<subsystem>_<what>_<unit> (e.g. "
    "fdtpu_train_step_seconds)")
def _check_metric_names(ctx: ModuleContext) -> Iterable[Finding]:
    rule = _rule_by_id("FDT106")
    bindings = _str_bindings(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr not in ("counter", "gauge", "histogram"):
            continue
        if not node.args:
            continue
        name = _const_str(node.args[0], bindings)
        if name is None:
            continue
        if not _METRIC_NAME_RE.match(name):
            yield _finding(
                rule, ctx, node,
                f"metric name {name!r} violates the fdtpu_* convention",
                detail=name)


# -- FDT107: donation documented but not declared --------------------------

@ast_rule(
    "FDT107", "donation-undeclared", "warning",
    "a step factory whose docstring documents donation but whose "
    "jax.jit calls never pass donate_argnums — callers believe buffers "
    "are reused while every step silently copies the full state.",
    "pass donate_argnums=(0,) (or donate_argnames) to the jit call, "
    "gated on the factory's donate flag")
def _check_donation_doc(ctx: ModuleContext) -> Iterable[Finding]:
    rule = _rule_by_id("FDT107")
    for info in ctx.functions:
        node = info.node
        if not node.name.startswith("make_"):
            continue
        doc = ast.get_docstring(node) or ""
        if "donat" not in doc.lower():
            continue
        jit_calls = [
            n for n in ctx.own_nodes(info)
            if isinstance(n, ast.Call) and _dotted(n.func).split(".")[-1] == "jit"
        ]
        if not jit_calls:
            continue
        if not any(
            k.arg in ("donate_argnums", "donate_argnames")
            for c in jit_calls for k in c.keywords
        ):
            yield _finding(
                rule, ctx, node,
                f"{info.qualname}() documents donation but none of its "
                f"{len(jit_calls)} jax.jit call(s) pass donate_argnums",
                detail=info.qualname)
