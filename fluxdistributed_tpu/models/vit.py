"""Vision Transformer family — net-new model scope beyond the reference.

The reference ships CNNs only (Metalhead ResNets, README.md:27); ViT-L/16
is one of this framework's BASELINE configs (BASELINE.json "configs").
Built TPU-first:

* NHWC patchify via a strided conv (one MXU-friendly matmul per patch),
* bf16 compute / f32 params, f32 softmax and layernorm statistics,
* the attention implementation is *pluggable* (``attn_fn``) so the same
  module runs single-device XLA attention, the Pallas flash kernel, or
  ring-attention context parallelism without touching model code,
* no python control flow on traced values — whole model jit/scan safe.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..ops.attention import dot_product_attention
from .common import maybe_remat

__all__ = ["ViT", "vit_tiny", "vit_b16", "vit_l16", "vit_h14"]

AttnFn = Callable  # (q, k, v) -> out, all [B, T, H, D]


class MlpBlock(nn.Module):
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    dropout: float = 0.0
    gelu_exact: bool = False  # erf GELU (torch default) vs tanh approx (TPU-fast)

    @nn.compact
    def __call__(self, x, *, train: bool):
        d = x.shape[-1]
        x = nn.Dense(self.mlp_dim, dtype=self.dtype)(x)
        x = nn.gelu(x, approximate=not self.gelu_exact)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.Dense(d, dtype=self.dtype)(x)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return x


class MultiHeadAttention(nn.Module):
    """QKV projection + pluggable core attention + output projection."""

    num_heads: int
    dtype: Any = jnp.bfloat16
    attn_fn: Optional[AttnFn] = None

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        assert d % self.num_heads == 0, "embed dim must divide num_heads"
        head_dim = d // self.num_heads
        qkv = nn.DenseGeneral(
            (3, self.num_heads, head_dim), axis=-1, dtype=self.dtype, name="qkv"
        )(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = self.attn_fn if self.attn_fn is not None else dot_product_attention
        out = attn(q, k, v)  # [B, T, H, Dh]
        return nn.DenseGeneral(d, axis=(-2, -1), dtype=self.dtype, name="out")(out)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    dropout: float = 0.0
    attn_fn: Optional[AttnFn] = None
    gelu_exact: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        # train is positional-or-keyword so nn.remat can mark it static
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = MultiHeadAttention(self.num_heads, dtype=self.dtype, attn_fn=self.attn_fn)(y)
        y = nn.Dropout(self.dropout, deterministic=not train)(y)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = MlpBlock(
            self.mlp_dim, dtype=self.dtype, dropout=self.dropout,
            gelu_exact=self.gelu_exact,
        )(y, train=train)
        return x + y


class ViT(nn.Module):
    """Vision Transformer (classification head, mean-pool token readout).

    Mean pooling over tokens instead of a class token keeps the sequence
    dimension uniform — a deliberate choice so the token axis can be
    sharded (sequence/context parallelism) without special-casing a
    non-divisible extra token.
    """

    patch: int = 16
    depth: int = 12
    dim: int = 768
    num_heads: int = 12
    mlp_dim: int = 3072
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    dropout: float = 0.0
    attn_fn: Optional[AttnFn] = None
    # torchvision-compat switches (models/torch_import.py): class-token
    # readout instead of mean pooling, and exact (erf) GELU.  Defaults
    # stay mean-pool + tanh GELU — the SP-shardable, TPU-fast form.
    use_class_token: bool = False
    gelu_exact: bool = False
    # rematerialize each encoder block in the backward pass (activation
    # memory O(1 block) for ~1 extra forward of FLOPs)
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = jnp.asarray(x, self.dtype)
        p = self.patch
        x = nn.Conv(
            self.dim, (p, p), strides=(p, p), padding="VALID",
            dtype=self.dtype, name="patch_embed",
        )(x)
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)
        ntok = h * w
        if self.use_class_token:
            cls = self.param(
                "cls_token", nn.initializers.zeros, (1, 1, self.dim), jnp.float32
            )
            x = jnp.concatenate(
                [jnp.broadcast_to(cls.astype(self.dtype), (b, 1, c)), x], axis=1
            )
            ntok += 1
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, ntok, self.dim), jnp.float32
        )
        x = x + pos.astype(self.dtype)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        block_cls = maybe_remat(EncoderBlock, self.remat, train_argnum=2)
        for i in range(self.depth):
            x = block_cls(
                self.num_heads, self.mlp_dim, dtype=self.dtype,
                dropout=self.dropout, attn_fn=self.attn_fn,
                gelu_exact=self.gelu_exact, name=f"block{i}",
            )(x, train)
        x = nn.LayerNorm(dtype=self.dtype, name="final_norm")(x)
        x = x[:, 0] if self.use_class_token else x.mean(axis=1)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def vit_tiny(num_classes: int = 10, **kw) -> ViT:
    """Tiny config for tests/dryruns (not a published variant)."""
    return _vit(kw, patch=4, depth=2, dim=64, num_heads=4, mlp_dim=128,
                num_classes=num_classes)


def _vit(kw: dict, **defaults) -> ViT:
    for key, val in defaults.items():
        kw.setdefault(key, val)
    return ViT(**kw)


def vit_b16(num_classes: int = 1000, **kw) -> ViT:
    return _vit(kw, patch=16, depth=12, dim=768, num_heads=12, mlp_dim=3072,
                num_classes=num_classes)


def vit_l16(num_classes: int = 1000, **kw) -> ViT:
    return _vit(kw, patch=16, depth=24, dim=1024, num_heads=16, mlp_dim=4096,
                num_classes=num_classes)


def vit_h14(num_classes: int = 1000, **kw) -> ViT:
    return _vit(kw, patch=14, depth=32, dim=1280, num_heads=16, mlp_dim=5120,
                num_classes=num_classes)
