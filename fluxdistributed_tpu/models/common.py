"""Shared model-building helpers."""

from __future__ import annotations

from flax import linen as nn

__all__ = ["maybe_remat"]


def maybe_remat(block_cls, enabled: bool, train_argnum: int | None = None):
    """Wrap a block Module class in ``nn.remat`` when ``enabled``.

    ``train_argnum`` marks the block's ``train`` flag static so flax's
    remat does not trace it into a ``bool[]`` tracer (which would break
    ``deterministic=not train``).  Argnums count ``self``: for
    ``__call__(self, x, train)`` pass 2 — and the call site must pass
    ``train`` POSITIONALLY (flax remat traces kwargs regardless of
    static_argnums).  Blocks whose ``__call__`` takes no train flag
    (ResNet blocks — BatchNorm mode is baked in via the ``norm``
    partial) pass ``None``.

    Remat callers must also pin each block's ``name=`` to the unwrapped
    auto-name: the wrapper class is named ``Checkpoint<Block>`` and
    would otherwise rename flax scopes, orphaning checkpoints and
    imported torch weights (asserted by ``tests/test_remat.py``).
    """
    if not enabled:
        return block_cls
    if train_argnum is None:
        return nn.remat(block_cls)
    return nn.remat(block_cls, static_argnums=(train_argnum,))
