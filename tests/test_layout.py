"""dp x fsdp x tp layouts + the auto-layout picker (parallel/layout.py).

The acceptance bar (ISSUE 15): ``layout.pick()`` selects a fitting
layout on a topology where plain dp provably does NOT fit — exercised
BOTH ways with explicit HBM budgets on the 8-virtual-device CPU mesh
(generous budget → dp wins the collective-ledger tiebreak; squeezed
budget → dp is excluded by the same ``rank_memory`` ranking bin/fit.py
uses and a sharded layout is chosen) — and ``bin/driver.py --layout
auto`` trains with the choice (slow tier, subprocess).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from fluxdistributed_tpu import optim
from fluxdistributed_tpu.parallel import layout as layout_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lm(dim=128, vocab=256, mlp=512):
    from fluxdistributed_tpu.models.transformer_lm import TransformerLM

    return TransformerLM(vocab=vocab, dim=dim, depth=2, num_heads=4,
                         mlp_dim=mlp, dtype=jax.numpy.float32)


# --------------------------------------------------------------- layouts

def test_presets_cover_8_devices():
    cands = layout_lib.layout_candidates(8)
    names = {c.name for c in cands}
    assert {"dp", "fsdp", "tp", "dp_fsdp", "fsdp_tp",
            "dp_fsdp_tp"} <= names
    for c in cands:
        assert c.devices() == 8, c
    # joint batch axes and shard counts
    lay = layout_lib.resolve_layout("dp_fsdp", 8)
    assert lay.batch_axes == ("data", "fsdp")
    assert lay.batch_shards == 8 and lay.tp == 1


def test_resolve_layout_errors():
    with pytest.raises(layout_lib.LayoutError, match="unknown layout"):
        layout_lib.resolve_layout("nope", 8)
    with pytest.raises(layout_lib.LayoutError, match="does not exist"):
        layout_lib.resolve_layout("dp_fsdp_tp", 4)
    with pytest.raises(layout_lib.LayoutError, match="covers 4"):
        layout_lib.resolve_layout(
            layout_lib.Layout("x", dp=2, fsdp=2), 8)
    lay = layout_lib.resolve_layout("dp", 8)
    with pytest.raises(layout_lib.LayoutError, match="do not match"):
        lay.validate_mesh(
            layout_lib.resolve_layout("fsdp", 8).build_mesh())


def test_tp_layout_without_rules_table_rejected():
    """A tp>1 layout on a model family with no tensor-parallel table
    would silently replicate over the model axis — rejected with the
    fix named."""
    from fluxdistributed_tpu.models.simple import SimpleCNN
    from fluxdistributed_tpu.parallel.dp import TrainState

    model = SimpleCNN(num_classes=4, features=8)
    lay = layout_lib.resolve_layout("fsdp_tp", 8)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8, 8, 3), np.float32),
                        train=True)["params"]
    state = TrainState.create(params, optim.adam(1e-3))
    with pytest.raises(layout_lib.LayoutError, match="no tensor-parallel"):
        layout_lib.state_specs_for(model, state, lay, lay.build_mesh())


# ---------------------------------------------------------------- picker

@pytest.fixture(scope="module")
def priced():
    """One pricing sweep (abstract compiles — no parameter buffer ever
    allocates) reused by every budget scenario below."""
    model = _lm()
    batch = {"tokens": jax.ShapeDtypeStruct((16, 32), np.int32)}
    rows = layout_lib.price_layouts(model, batch, optim.adam(1e-3))
    return model, batch, rows


def test_pick_generous_budget_prefers_dp_by_ledger(priced):
    model, batch, rows = priced
    rep = layout_lib.pick(model, batch, optim.adam(1e-3),
                          hbm_bytes=1e9, rows=rows)
    assert rep.chosen.name == "dp"
    by = {r["layout"]: r for r in rep.rows}
    # dp fits AND moves the fewest bytes (one grad all-reduce vs
    # fsdp's per-layer gather/scatter traffic) — the tiebreak truth
    assert by["dp"]["fits"] is True
    fitting = [r for r in rep.rows if r.get("fits")]
    assert by["dp"]["comms_bytes"] == min(
        r["comms_bytes"] for r in fitting)
    # the report is JSON-serializable with the ranking intact
    doc = rep.to_json()
    assert doc["chosen"] == "dp" and json.dumps(doc)


def test_pick_squeezed_budget_excludes_dp(priced):
    """THE acceptance scenario: a budget below dp's peak but above the
    sharded layouts' — dp provably does not fit, the picker selects a
    fitting sharded layout instead, through the same rank_memory
    ranking bin/fit.py applies."""
    model, batch, rows = priced
    by = {r["layout"]: r for r in rows}
    dp_peak = by["dp"]["peak_bytes"]
    fsdp_peak = by["fsdp"]["peak_bytes"]
    assert fsdp_peak < dp_peak  # sharding genuinely shrinks the step
    budget = (dp_peak + fsdp_peak) / 2
    rep = layout_lib.pick(model, batch, optim.adam(1e-3),
                          hbm_bytes=budget, rows=rows)
    assert rep.chosen.name != "dp"
    chosen_row = next(r for r in rep.rows
                      if r["layout"] == rep.chosen.name)
    assert chosen_row["fits"] is True
    assert next(r for r in rep.rows
                if r["layout"] == "dp")["fits"] is False


def test_pick_nothing_fits_raises_with_report(priced):
    model, batch, rows = priced
    with pytest.raises(layout_lib.LayoutError, match="no layout fits") \
            as ei:
        layout_lib.pick(model, batch, optim.adam(1e-3),
                        hbm_bytes=1000.0, rows=rows)
    rep = ei.value.report
    assert rep.chosen is None and len(rep.rows) == len(rows)
    assert "does not fit" in rep.describe().lower() \
        or "DOES NOT FIT" in rep.describe()


def test_pick_no_budget_ranks_by_ledger_only(priced):
    model, batch, rows = priced
    rep = layout_lib.pick(model, batch, optim.adam(1e-3), rows=rows)
    assert rep.budget_bytes is None  # CPU reports no memory_stats
    assert rep.chosen is not None
    assert "collective" in rep.reason


def test_pick_survives_unavailable_ledger_and_custom_layouts(priced):
    """Review regressions: (1) a fitting row whose HLO-ledger
    extraction failed (comms_bytes=None) must not crash the reason
    string nor read as 'invalid' in the report; (2) rows priced for a
    CUSTOM candidate set re-pick without layouts= — the chosen Layout
    rebuilds from the row's recorded sizes instead of StopIteration."""
    import copy

    model, batch, rows = priced
    crippled = copy.deepcopy(rows)
    for r in crippled:
        r.pop("comms", None)
        r["comms_bytes"] = None
        r.pop("comms_bytes_per_axis", None)
        r["comms_unavailable"] = "Boom: synthetic"
    rep = layout_lib.pick(model, batch, optim.adam(1e-3),
                          hbm_bytes=1e9, rows=crippled)
    assert rep.chosen is not None
    assert "ledger unavailable" in rep.reason
    text = rep.describe()
    assert "invalid: None" not in text
    assert "collective ledger unavailable" in text
    # custom-name rows, no layouts= at pick time
    renamed = copy.deepcopy(rows)
    for r in renamed:
        r["layout"] = "custom_" + r["layout"]
    rep2 = layout_lib.pick(model, batch, optim.adam(1e-3),
                           hbm_bytes=1e9, rows=renamed)
    assert rep2.chosen.name.startswith("custom_")
    assert rep2.chosen.devices() == 8  # rebuilt from the row's sizes
    # a custom layout SHARING a preset name must resolve to the sizes
    # that were actually priced, not the preset's
    custom = layout_lib.Layout("dp_fsdp", dp=4, fsdp=2)
    priced_custom = layout_lib.price_layouts(
        model, batch, optim.adam(1e-3), layouts=[custom])
    rep3 = layout_lib.pick(model, batch, optim.adam(1e-3),
                           hbm_bytes=1e9, rows=priced_custom)
    assert (rep3.chosen.dp, rep3.chosen.fsdp) == (4, 2), rep3.chosen


def test_pick_budget_without_memory_model_degrades(priced):
    """Review regression: budget given but NO row has a measured peak
    (memory_analysis-less build) — ledger-only degradation with the
    honest reason, never a false 'exceeds the budget' failure."""
    import copy

    model, batch, rows = priced
    dark = copy.deepcopy(rows)
    for r in dark:
        r.pop("memory", None)
        r["peak_bytes"] = None
    rep = layout_lib.pick(model, batch, optim.adam(1e-3),
                          hbm_bytes=1e9, rows=dark)
    assert rep.chosen is not None
    assert "memory model unavailable" in rep.reason


def test_rank_memory_is_the_fit_checker_ranking(priced):
    """The picker consumes bin/fit.py's ranking, not a re-derivation:
    feeding the priced rows through rank_memory reproduces the fit
    verdicts the pick reports."""
    from fluxdistributed_tpu.obs.memstats import rank_memory

    model, batch, rows = priced
    by = {r["layout"]: r for r in rows}
    budget = by["dp"]["peak_bytes"] - 1
    ranked = {r["variant"]: r for r in rank_memory(
        {r["layout"]: {"memory": r.get("memory")} for r in rows
         if "invalid" not in r}, budget)}
    rep = layout_lib.pick(model, batch, optim.adam(1e-3),
                          hbm_bytes=budget, rows=rows)
    for r in rep.rows:
        if "invalid" in r:
            continue
        assert r["fits"] == ranked[r["layout"]]["fits"]
        assert r["headroom_bytes"] == ranked[r["layout"]]["headroom_bytes"]


@pytest.mark.slow
def test_bench_layout_pick_stamp():
    """bench.py's layout_pick stamp: chosen layout + per-candidate
    ranking rows, never raising (the best-effort stamp contract) —
    budget honestly None on the CPU mesh."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_for_layout", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    s = bench.layout_pick_stamp()
    assert s.get("chosen") in {"dp", "fsdp", "tp", "dp_fsdp",
                               "fsdp_tp", "dp_fsdp_tp"}, s
    assert s["budget_bytes"] is None  # CPU: ledger-only ranking
    assert {r["layout"] for r in s["rows"]} >= {"dp", "fsdp"}


# ----------------------------------------------------------- driver e2e

@pytest.mark.slow
def test_driver_layout_auto_trains(tmp_path):
    """bin/driver.py --layout auto on the 8-virtual-device CPU mesh:
    picks, prints the ranking, writes the report artifact, and TRAINS
    with the chosen layout."""
    report = tmp_path / "pick.json"
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "driver.py"),
         "--model", "lm_tiny", "--dataset", "synthetic-text",
         "--vocab", "64", "--seqlen", "32", "--batch-size", "16",
         "--cycles", "3", "--layout", "auto", "--hbm-bytes", "1e9",
         "--platform", "cpu", "--local-devices", "8",
         "--layout-report", str(report)],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "PYTHONPATH": REPO})
    assert p.returncode == 0, p.stdout + p.stderr
    assert "layout pick:" in p.stdout and "done: 3 steps" in p.stdout
    doc = json.loads(report.read_text())
    assert doc["schema"] == "fdtpu-layout-pick/v1"
    assert doc["chosen"] in {r["layout"] for r in doc["rows"]}
    assert any(r.get("fits") for r in doc["rows"])
