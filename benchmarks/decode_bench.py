#!/usr/bin/env python
"""Decode throughput: continuous batching vs sequential ``generate()``.

The serving-side companion of lm_bench.py (training tokens/sec).  Two
questions, per concurrency level:

1. **prefill vs steady-state decode tokens/sec** — prompt ingestion is
   matmul-dense and parallel over positions; decode is one token per
   step and memory-bound.  The ratio is the reason slot-based
   continuous batching exists.
2. **continuous batching vs sequential** — aggregate NEW tokens/sec for
   C concurrent requests through the slot engine (one fixed-shape
   compiled step serves all live requests) vs the same C requests run
   one-at-a-time through ``models.generate``.  Two sequential baselines
   are recorded: the AS-SHIPPED path (a fresh ``generate()`` call per
   request, which re-traces its scan every call — what ``bin/
   generate.py`` serving actually cost before this engine), and an
   idealized CACHED program (the whole sampler under one ``jax.jit``,
   reused across requests — the strongest sequential opponent).  The
   headline ``speedup_vs_sequential`` is against the as-shipped path;
   ``speedup_vs_sequential_cached`` tells the honest steady-state story
   (on a compute-bound CPU it hovers near the batch-GEMM amortization
   limit; the TPU session rows measure the memory-bound regime where
   slot batching actually pays).

Each row also records the engine's compile counts: steady-state decode
must hold at ONE compiled step program after warmup — a recompile in
the serving loop is a bug (arXiv:1810.09868's fixed-shape lesson).

A third section compares the two CACHE LAYOUTS (``--layouts``):

3. **paged vs dense** — mid-flight KV HBM bytes per live token (the
   paged pool allocates blocks as cursors advance, so live bytes track
   live tokens; dense reserves ``max_slots × max_len`` rows whatever is
   resident), steady-state decode tok/s under each layout, and the
   chunked-prefill headline: **TTFT of a short prompt admitted behind a
   ``max_len``-sized prompt**.  Dense whole-prefill makes the short
   request wait out the long prompt's entire prefill; paged chunked
   prefill interleaves, so the short request's first token arrives
   after a few chunk-sized ticks.

    python benchmarks/decode_bench.py --platform cpu     # CPU rows (CI)
    python benchmarks/decode_bench.py --model lm_small --vocab 32000 \
        --prompt-len 128 --new-tokens 256                # TPU session row
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="lm_tiny",
                    choices=["lm_tiny", "lm_small", "lm_medium"])
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--concurrency", default="1,4,16",
                    help="comma-separated request counts")
    ap.add_argument("--max-slots", type=int, default=16,
                    help="engine slot count (capped at each row's C)")
    ap.add_argument("--kv-heads", type=int, default=None)
    ap.add_argument("--window", type=int, default=None)
    ap.add_argument("--sinks", type=int, default=0)
    ap.add_argument("--dtype", default="auto", choices=["auto", "bf16", "f32"],
                    help="model compute dtype: auto = bf16 on TPU (native "
                         "MXU format), f32 elsewhere (CPU emulates bf16 "
                         "matmuls ~8x slower — both serving paths use the "
                         "same model, so the comparison stays fair)")
    ap.add_argument("--platform", default=None, help="force platform (e.g. cpu)")
    ap.add_argument("--layouts", default="dense,paged",
                    help="cache layouts for the comparison section "
                         "(comma-separated; 'none' skips it)")
    ap.add_argument("--kv-block-size", type=int, default=16,
                    help="paged layout: rows per KV block")
    ap.add_argument("--kv-blocks", type=int, default=None,
                    help="paged layout: pool size in blocks per layer "
                         "(default: full capacity — pass a smaller pool "
                         "to measure a sub-capacity reserved footprint)")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="paged layout: prompt positions per prefill "
                         "chunk (default: kv block size x 2)")
    ap.add_argument("--fastpath", default="xla:none,pallas:none,pallas:int8",
                    help="Pallas fast-path section: comma-separated "
                         "attention_impl:kv_dtype combos measured on the "
                         "paged layout at --fastpath-max-len reserved "
                         "rows ('none' skips the section)")
    ap.add_argument("--fastpath-max-len", type=int, default=1024,
                    help="fast-path section: reserved rows per slot — "
                         "the decode-kernel win scales with reserved/"
                         "live, like production caches sized for the "
                         "longest request")
    args = ap.parse_args()

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import numpy as np

    from fluxdistributed_tpu import models
    from fluxdistributed_tpu.serve import LMEngine, Request, Scheduler

    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    plen, new = args.prompt_len, args.new_tokens
    total = plen + new
    if args.dtype == "auto":
        dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32
    else:
        dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    model = getattr(models, args.model)(
        vocab=args.vocab, num_kv_heads=args.kv_heads, window=args.window,
        sinks=args.sinks, dtype=dtype)
    rng = np.random.default_rng(0)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 2), np.int32), train=False
    )["params"]
    dm = model.clone(decode=True)

    def prompts(c):
        return [rng.integers(0, args.vocab, plen).astype(np.int32) for _ in range(c)]

    def pct_ms(hist, q, digits=2):
        # NaN (empty histogram) must not leak into the JSON rows
        import math

        v = hist.percentile(q)
        return None if math.isnan(v) else round(v * 1e3, digits)

    # idealized sequential baseline: the whole sampler under ONE jit so
    # repeated requests reuse a compiled program
    seq_fn = jax.jit(
        lambda p: models.generate(dm, params, p, total_len=total))

    def run_sequential_cached(ps):
        t0 = time.perf_counter()
        for p in ps:
            np.asarray(seq_fn(p[None]))
        return time.perf_counter() - t0

    def run_sequential_shipped(p):
        # the pre-engine serving path: one bare generate() per request,
        # re-tracing its scan every call.  Identical independent calls,
        # so one timed call IS the per-request cost (scaled to C below).
        t0 = time.perf_counter()
        np.asarray(models.generate(dm, params, p[None], total_len=total))
        return time.perf_counter() - t0

    for c in [int(x) for x in args.concurrency.split(",")]:
        slots = max(1, min(args.max_slots, c))
        engine = LMEngine(model, params, max_slots=slots, max_len=total,
                          buckets=(plen,))
        # warmup: compile prefill/insert/decode once (also warms the
        # sequential program via one throwaway generate call)
        warm = Scheduler(engine)
        warm.generate_all([Request(prompt=list(range(2)), max_new_tokens=2)])
        np.asarray(seq_fn(prompts(1)[0][None]))
        compiles_before = engine.compile_stats()

        ps = prompts(c)
        seq_cached_sec = run_sequential_cached(ps)
        seq_shipped_sec = run_sequential_shipped(ps[0]) * c

        sched = Scheduler(engine, max_queue=max(c, 1))
        reqs = [Request(prompt=list(p), max_new_tokens=new) for p in ps]
        t0 = time.perf_counter()
        sched.generate_all(reqs)
        eng_sec = time.perf_counter() - t0
        m = sched.metrics()
        # tail percentiles straight off the registry histograms via the
        # SHARED bucket-percentile helper (obs.metrics) — the same math
        # the /metrics p50/p95 rollup gauges render
        ttft_hist = sched.registry.get("fdtpu_serve_ttft_seconds")
        tbt_hist = sched.registry.get("fdtpu_serve_tbt_seconds")
        compiles_after = engine.compile_stats()

        seq_tps = c * new / seq_shipped_sec
        seq_cached_tps = c * new / seq_cached_sec
        eng_tps = c * new / eng_sec
        no_recompile = (
            compiles_after["decode_compiles"] == compiles_before["decode_compiles"] == 1
        )
        print(json.dumps({
            "metric": f"{args.model} continuous-batching decode throughput "
                      f"({platform}, {jnp.dtype(dtype).name}, C={c}, "
                      f"slots={slots}, P={plen}, N={new}, "
                      f"vocab {args.vocab})",
            "value": round(eng_tps, 2),
            "unit": "new tokens/sec aggregate",
            "concurrency": c,
            "sequential_tokens_per_sec": round(seq_tps, 2),
            "speedup_vs_sequential": round(eng_tps / seq_tps, 2),
            "sequential_cached_tokens_per_sec": round(seq_cached_tps, 2),
            "speedup_vs_sequential_cached": round(eng_tps / seq_cached_tps, 2),
            "prefill_tokens_per_sec": round(m["prefill_tokens_per_sec"], 2),
            "steady_decode_tokens_per_sec": round(
                m["decode_tokens_per_sec"], 2),
            "ttft_ms_avg": round(m["ttft_sec_avg"] * 1e3, 2),
            "ttft_ms_p50": pct_ms(ttft_hist, 50),
            "ttft_ms_p95": pct_ms(ttft_hist, 95),
            "tbt_ms_p50": pct_ms(tbt_hist, 50, 3),
            "tbt_ms_p95": pct_ms(tbt_hist, 95, 3),
            "decode_compiles": compiles_after["decode_compiles"],
            "prefill_compiles": compiles_after["prefill_compiles"],
            "no_recompile_after_warmup": bool(no_recompile),
        }))
        if not no_recompile:
            print(f"WARNING: decode step recompiled mid-serve "
                  f"(compiles {compiles_before} -> {compiles_after})",
                  file=sys.stderr)

    # ---- layout comparison: paged vs dense -------------------------------
    layouts = [l for l in args.layouts.split(",") if l and l != "none"]
    chunk = args.prefill_chunk or args.kv_block_size * 2
    long_len = min(8 * plen, 2048)
    short_len = max(4, plen // 8)
    new_cmp = min(new, 32)
    cap = long_len + new_cmp  # per-slot budget: a max_len-sized prompt
    ttft = {}
    for layout in layouts:
        kw = (dict(layout="paged", kv_block_size=args.kv_block_size,
                   kv_blocks=args.kv_blocks, prefill_chunk=chunk)
              if layout == "paged" else
              dict(buckets=(max(short_len, 16), long_len)))
        engine = LMEngine(model, params, max_slots=2, max_len=cap, **kw)
        # warm every program (both prompt shapes) outside the timings
        warm = Scheduler(engine)
        warm.generate_all([
            Request(prompt=list(range(2)), max_new_tokens=2),
            Request(prompt=list(range(min(long_len, 2 * chunk))),
                    max_new_tokens=2)])
        warm.close()

        # TTFT probe: a short prompt admitted BEHIND a max_len-sized
        # one.  Median of 3 — the TTFTs are small enough that one GC
        # pause or scheduler hiccup would otherwise dominate the ratio
        samples = []
        for _ in range(3):
            sched = Scheduler(engine, max_queue=4)
            longp = Request(
                prompt=list(rng.integers(0, args.vocab, long_len)),
                max_new_tokens=new_cmp)
            shortp = Request(
                prompt=list(rng.integers(0, args.vocab, short_len)),
                max_new_tokens=new_cmp)
            sched.submit(longp)
            sched.submit(shortp)
            sched.run_until_idle()
            samples.append(shortp.first_token_at - shortp.submitted_at)
            sched.close()
        ttft[layout] = sorted(samples)[1]

        # occupancy probe: mid-flight KV bytes per live token
        sched = Scheduler(engine, max_queue=4)
        reqs = [Request(prompt=list(rng.integers(0, args.vocab, plen)),
                        max_new_tokens=new_cmp) for _ in range(2)]
        for r in reqs:
            sched.submit(r)
        # first-token (not state) is the barrier: with a tiny
        # --new-tokens a request can already be DONE by the time the
        # other goes active, and "done" would spin this loop forever
        while any(r.first_token_at is None for r in reqs):
            sched.step()
        for _ in range(4):
            sched.step()
        kv = engine.kv_cache_bytes()
        blocks_now = engine.pool_stats().get("kv_blocks_active")
        live_tokens = sum(len(r.prompt) + len(r.generated) for r in reqs)
        sched.run_until_idle()
        m = sched.metrics()
        sched.close()
        print(json.dumps({
            "metric": f"{args.model} serve cache layout ({platform}, "
                      f"{jnp.dtype(dtype).name}, layout={layout}, "
                      f"slots=2, max_len={cap}"
                      + (f", block={args.kv_block_size}, chunk={chunk}"
                         if layout == "paged" else "") + ")",
            "value": round(kv["live"] / live_tokens, 1),
            "unit": "live KV bytes per live token (mid-flight)",
            "layout": layout,
            "kv_bytes_reserved": kv["reserved"],
            "kv_bytes_live": kv["live"],
            "live_tokens": live_tokens,
            "reserved_bytes_per_live_token": round(
                kv["reserved"] / live_tokens, 1),
            "steady_decode_tokens_per_sec": round(
                m["decode_tokens_per_sec"], 2),
            "short_ttft_behind_long_prompt_ms": round(ttft[layout] * 1e3, 2),
            "long_prompt_len": long_len,
            "short_prompt_len": short_len,
            "decode_compiles": m["decode_compiles"],
            "prefill_compiles": m["prefill_compiles"],
            "kv_blocks_total": m.get("kv_blocks_total"),
            "kv_blocks_active_midflight": blocks_now,
        }))
    if "dense" in ttft and "paged" in ttft and ttft["paged"] > 0:
        print(json.dumps({
            "metric": f"{args.model} chunked-prefill TTFT win "
                      f"({platform}: short prompt of {short_len} behind a "
                      f"{long_len}-token prompt, chunk={chunk})",
            "value": round(ttft["dense"] / ttft["paged"], 2),
            "unit": "x shorter TTFT (dense whole-prefill / paged chunked)",
            "ttft_dense_ms": round(ttft["dense"] * 1e3, 2),
            "ttft_paged_ms": round(ttft["paged"] * 1e3, 2),
        }))

    # ---- Pallas fast path: flash-decode kernel + quantized KV ------------
    combos = [c for c in args.fastpath.split(",") if c and c != "none"]
    if not combos:
        return
    cap = args.fastpath_max_len
    fp_slots = 4
    new_fp = min(new, 32)
    results = {}
    for combo in combos:
        impl, _, kvd = combo.partition(":")
        kvd = kvd or "none"
        engine = LMEngine(
            model, params, max_slots=fp_slots, max_len=cap, layout="paged",
            kv_block_size=args.kv_block_size, prefill_chunk=chunk,
            attention_impl=impl, kv_dtype=None if kvd == "none" else kvd)
        warm = Scheduler(engine)
        warm.generate_all([Request(prompt=list(range(2)), max_new_tokens=2)])
        warm.close()
        sched = Scheduler(engine, max_queue=fp_slots)
        reqs = [Request(prompt=list(rng.integers(0, args.vocab, plen)),
                        max_new_tokens=new_fp) for _ in range(fp_slots)]
        for r in reqs:
            sched.submit(r)
        while any(r.first_token_at is None for r in reqs):
            sched.step()
        for _ in range(4):
            sched.step()
        kv = engine.kv_cache_bytes()
        live_tokens = sum(len(r.prompt) + len(r.generated) for r in reqs)
        sched.run_until_idle()
        m = sched.metrics()
        sched.close()
        row = {
            "metric": f"{args.model} paged decode fast path ({platform}, "
                      f"{jnp.dtype(dtype).name}, attention_impl={impl}, "
                      f"kv_dtype={kvd}, slots={fp_slots}, max_len={cap}, "
                      f"P={plen}, N={new_fp})",
            "value": round(m["decode_tokens_per_sec"], 2),
            "unit": "steady decode tokens/sec",
            "attention_impl": impl,
            "kv_dtype": kvd,
            "live_kv_bytes_per_token": round(kv["live"] / live_tokens, 1),
            "kv_bytes_reserved": kv["reserved"],
            "decode_compiles": m["decode_compiles"],
        }
        results[(impl, kvd)] = row
        print(json.dumps(row))
    base = results.get(("xla", "none"))
    fast = results.get(("pallas", "none"))
    if base and fast and base["value"]:
        print(json.dumps({
            "metric": f"{args.model} flash-decode engine win ({platform}: "
                      f"paged, max_len={cap}, live≈{plen + new_fp})",
            "value": round(fast["value"] / base["value"], 2),
            "unit": "x steady decode tokens/sec vs the XLA decode path",
            "xla_tokens_per_sec": base["value"],
            "pallas_tokens_per_sec": fast["value"],
        }))
    q8 = results.get(("pallas", "int8"))
    ref8 = fast or base
    if q8 and ref8 and q8["live_kv_bytes_per_token"]:
        print(json.dumps({
            "metric": f"{args.model} int8 KV cache win ({platform}: paged, "
                      f"max_len={cap})",
            "value": round(ref8["live_kv_bytes_per_token"]
                           / q8["live_kv_bytes_per_token"], 2),
            "unit": "x fewer live KV bytes per live token vs "
                    f"{jnp.dtype(dtype).name} storage",
            "bytes_per_token_full": ref8["live_kv_bytes_per_token"],
            "bytes_per_token_int8": q8["live_kv_bytes_per_token"],
            "decode_tokens_per_sec_int8": q8["value"],
        }))


if __name__ == "__main__":
    main()
