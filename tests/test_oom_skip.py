"""OOM fault-tolerance integration tests.

The reference catches device OOM inside its task loop and skips the
batch (src/ddp_tasks.jl:230-238) with a ``num_missed`` counter that is
declared but never incremented (:178, :240).  Here the counter is live
and the two guard branches (donated state, multi-host) raise with clear
messages — these tests exercise all three paths by injecting a failing
step_fn, the analog of the reference's ``TaskFailedException`` wrapping.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from fluxdistributed_tpu import optim
from fluxdistributed_tpu.data import SyntheticDataset
from fluxdistributed_tpu.models import resnet18
from fluxdistributed_tpu.train import prepare_training, train
from fluxdistributed_tpu.train.logging import NullLogger


def _task(cycles=4, donate=False):
    ds = SyntheticDataset(nsamples=64, nclasses=10, shape=(16, 16, 3))
    return prepare_training(
        resnet18(num_classes=10, dtype=jnp.float32),
        ds,
        optim.momentum(0.1, 0.9),
        batch_size=16,
        cycles=cycles,
        donate=donate,
    )


class _FakeOOM(Exception):
    pass


def _inject_oom_once(task, msg="RESOURCE_EXHAUSTED: fake injected OOM"):
    real = task.step_fn
    calls = {"n": 0}

    def failing(state, batch):
        calls["n"] += 1
        if calls["n"] == 1:
            raise _FakeOOM(msg)
        return real(state, batch)

    task.step_fn = failing
    return calls


def test_oom_skips_batch_and_continues():
    task = _task(cycles=4)
    _inject_oom_once(task)
    train(task, print_every=0, eval_every=0, logger=NullLogger())
    assert task.num_missed == 1
    # 4 cycles, first skipped -> 3 applied steps
    assert int(task.state.step) == 3


def test_non_oom_errors_propagate():
    task = _task(cycles=2)
    _inject_oom_once(task, msg="INVALID_ARGUMENT: something else entirely")
    with pytest.raises(_FakeOOM):
        train(task, print_every=0, eval_every=0, logger=NullLogger())
    assert task.num_missed == 0


def test_oom_with_donated_state_raises():
    class _DeletedLeaf:
        def is_deleted(self):
            return True

    task = _task(cycles=2, donate=True)

    def failing(state, batch):
        # simulate: buffers were donated to the failed execution
        from fluxdistributed_tpu.parallel.dp import TrainState

        task.state = TrainState(
            params={"w": _DeletedLeaf()},
            opt_state=state.opt_state,
            model_state=state.model_state,
            step=state.step,
        )
        raise _FakeOOM("RESOURCE_EXHAUSTED: fake injected OOM")

    task.step_fn = failing
    with pytest.raises(RuntimeError, match="donate=True"):
        train(task, print_every=0, eval_every=0, logger=NullLogger())


def test_oom_multihost_raises(monkeypatch):
    from fluxdistributed_tpu.parallel import multihost

    task = _task(cycles=2)
    _inject_oom_once(task)
    # Fake a 2-process world for the trainer's guard; keep the loader's
    # batch assembly single-process (it would otherwise try to stitch a
    # half-batch from each "process").
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost, "global_batch_put", jax.device_put)
    with pytest.raises(RuntimeError, match="multi-host"):
        train(task, print_every=0, eval_every=0, logger=NullLogger())
