"""Evaluation metrics.

Replaces the reference's top-k accuracy stack (``topkaccuracy``
src/utils.jl:39-45 with its ``maxk!`` partial-sort helper :20-37, used for
k in {1,5,10} at src/ddp_tasks.jl:129).  On TPU the partial sort becomes
``jax.lax.top_k``, which XLA lowers natively; the function is
jit-compatible so eval can run compiled on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["topkaccuracy", "onehot", "showpreds"]


def onehot(labels, nclasses: int):
    """One-hot encode integer labels — ``Flux.onehotbatch`` analog
    (src/imagenet.jl:47), batch-major."""
    return jax.nn.one_hot(labels, nclasses, dtype=jnp.float32)


def topkaccuracy(scores, labels, k: int = 5):
    """Fraction of rows whose true class is within the top-k scores.

    ``scores``: (batch, classes) — logits or probabilities (monotone
    equivalence makes softmax optional, unlike the reference which
    softmaxes first at src/ddp_tasks.jl:135).
    ``labels``: one-hot (batch, classes) or integer ids (batch,).
    """
    if labels.ndim == scores.ndim:
        labels = jnp.argmax(labels, axis=-1)
    k = min(k, scores.shape[-1])
    _, topk_idx = jax.lax.top_k(scores, k)
    hits = jnp.any(topk_idx == labels[:, None], axis=-1)
    return jnp.mean(hits.astype(jnp.float32))


def showpreds(logits, class_names=None, k: int = 3, names=None) -> str:
    """Pretty-print the top-k predictions per sample — the ``showpreds``
    table analog (src/utils.jl:47-71, used by the reference's Pluto
    webcam demo bin/pluto.jl:338-382).

    ``logits``: (batch, classes) host array; ``class_names``: optional
    list mapping class index → human-readable label; ``names``: optional
    per-sample row labels (e.g. file names).  Returns the formatted table
    (also suitable for ``print``).
    """
    import numpy as np

    logits = np.asarray(logits)
    probs = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    probs = np.asarray(probs)
    k = min(k, logits.shape[-1])
    lines = []
    for i in range(logits.shape[0]):
        order = np.argsort(-probs[i])[:k]
        row = names[i] if names is not None else f"sample {i}"
        lines.append(f"{row}:")
        for rank, c in enumerate(order, 1):
            label = class_names[c] if class_names is not None else f"class {c}"
            lines.append(f"  {rank}. {label:<40s} {probs[i, c]:7.4f}")
    return "\n".join(lines)
