#!/usr/bin/env python
"""Inference demo CLI — classify images with a trained checkpoint.

TPU-native replacement for the reference's Pluto inference notebook
(bin/pluto.jl): where the notebook fetches a trained BSON model from
JuliaHub job results (:52-124), captures a webcam frame via embedded
HTML/JS (:133-334) and prints the top-3 ImageNet labels (:338-382), this
CLI loads an orbax checkpoint produced by the trainer, preprocesses
images through the same native/PIL pipeline training uses, runs one
jitted forward pass, and prints the ``showpreds`` top-k table
(src/utils.jl:47-71 analog).

    python bin/infer.py --model resnet50 --checkpoint ckpts/ \
        --synset LOC_synset_mapping.txt cat.jpg dog.jpg

    # no checkpoint/images → random-init demo on a synthetic image
    python bin/infer.py --model resnet18 --num-classes 10
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("images", nargs="*", help="image files (JPEG/PNG)")
    p.add_argument("--model", default="resnet50",
                   help="model factory name in fluxdistributed_tpu.models")
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--checkpoint", default=None,
                   help="checkpoint dir from the trainer (latest step used; "
                        "random init if omitted). http(s)://-or-gs:// URLs "
                        "are fetched through the dataset source cache "
                        "(a remote .zip of the dir is unpacked) — the "
                        "reference notebook's trained-model download, "
                        "bin/pluto.jl:52-124")
    p.add_argument("--step", type=int, default=None, help="specific checkpoint step")
    p.add_argument("--torch-weights", default=None,
                   help=".pt/.pth file with a torchvision-layout ResNet "
                        "state_dict (the pretrained-weight path; analog of "
                        "the reference's getweights, src/preprocess.jl:9-24)."
                        " May be an http(s):// or gs:// URL (fetched+cached)")
    p.add_argument("--synset", default=None,
                   help="LOC_synset_mapping.txt for human-readable labels "
                        "(local path or http(s)://-/gs://-fetched)")
    p.add_argument("--topk", type=int, default=3,
                   help="predictions per image (reference demo: top-3)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--resize", type=int, default=256)
    p.add_argument("--platform", default=None,
                   help="force a JAX platform (e.g. 'cpu'); needed where "
                        "site hooks import jax before JAX_PLATFORMS applies")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import numpy as np

    from fluxdistributed_tpu import models as models_lib
    from fluxdistributed_tpu.data.preprocess import preprocess
    from fluxdistributed_tpu.ops import showpreds

    factory = getattr(models_lib, args.model, None)
    if factory is None:
        print(f"unknown model {args.model!r}", file=sys.stderr)
        return 2
    model = factory(num_classes=args.num_classes)

    from fluxdistributed_tpu.data.sources import fetch_artifact, fetch_checkpoint

    names = None
    if args.synset:
        from fluxdistributed_tpu.data.imagenet import labels

        table = labels(fetch_artifact(args.synset))
        names = [n.split(",")[0] for n in table.names]

    if args.images:
        batch = np.stack(
            [preprocess(p, crop=args.image_size, resize=args.resize) for p in args.images]
        )
        row_names = args.images
    else:
        print("(no images given — running a random-init demo on noise)")
        batch = np.random.default_rng(0).normal(
            0, 1, (1, args.image_size, args.image_size, 3)
        ).astype(np.float32)
        row_names = ["<synthetic>"]

    if args.torch_weights and args.checkpoint:
        print("--torch-weights and --checkpoint are mutually exclusive", file=sys.stderr)
        return 2
    if args.torch_weights:
        from fluxdistributed_tpu.models.torch_import import load_torch_weights_for

        try:
            model, variables = load_torch_weights_for(
                args.model, args.num_classes, fetch_artifact(args.torch_weights)
            )
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        print(f"loaded torch-layout weights from {args.torch_weights}")
    elif args.checkpoint:
        from fluxdistributed_tpu.train.checkpoint import load_checkpoint

        # raw (target-free) restore: works for checkpoints from ANY
        # optimizer — inference only needs params/model_state/step
        args.checkpoint = fetch_checkpoint(args.checkpoint)
        restored = load_checkpoint(args.checkpoint, step=args.step)
        variables = {"params": restored["params"], **restored.get("model_state", {})}
        print(f"restored checkpoint step {int(restored['step'])} from {args.checkpoint}")
    else:
        variables = model.init(jax.random.PRNGKey(0), batch[:1], train=False)

    @jax.jit
    def forward(variables, x):
        return model.apply(variables, x, train=False)

    logits = np.asarray(forward(variables, batch))
    print(showpreds(logits, class_names=names, k=args.topk, names=row_names))
    return 0


if __name__ == "__main__":
    sys.exit(main())
