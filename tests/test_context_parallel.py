"""Ring attention / Ulysses sequence parallelism on the 8-device mesh.

The invariant mirrors the reference's DP test strategy (sharded result
== single-device result, test/single_device.jl:115-168), applied to the
sequence axis: attention over a sequence sharded across 8 devices must
equal single-device attention on the full sequence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu.mesh import make_mesh
from fluxdistributed_tpu.ops.attention import dot_product_attention
from fluxdistributed_tpu.parallel.context import (
    make_ring_attention,
    make_ulysses_attention,
)


def _qkv(b=2, t=64, h=8, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d), jnp.float32) for k in ks)


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh({"seq": 8})


@pytest.fixture(scope="module")
def data_seq_mesh():
    return make_mesh({"data": 2, "seq": 4})


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_single_device(seq_mesh, causal):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    attn = make_ring_attention(seq_mesh, causal=causal)
    out = jax.jit(attn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_single_device(seq_mesh, causal):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    attn = make_ulysses_attention(seq_mesh, causal=causal)
    out = jax.jit(attn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_2d_mesh_data_and_seq(data_seq_mesh):
    """Batch on 'data' × sequence on 'seq' — the composed layout."""
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=True)
    attn = make_ring_attention(data_seq_mesh, batch_axis="data", causal=True)
    out = jax.jit(attn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_grads_match(seq_mesh):
    q, k, v = _qkv(t=32)
    attn = make_ring_attention(seq_mesh, causal=True)

    def loss_ring(q, k, v):
        return (attn(q, k, v) ** 2).sum()

    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    ge = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, ge):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_single_device(seq_mesh, causal):
    """Pallas flash kernel as the ring's per-hop block compute."""
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    attn = make_ring_attention(seq_mesh, causal=causal, impl="flash",
                               block_q=8, block_k=8)
    out = jax.jit(attn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_flash_2d_mesh_data_and_seq(data_seq_mesh):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=True)
    attn = make_ring_attention(data_seq_mesh, batch_axis="data", causal=True,
                               impl="flash", block_q=16, block_k=16)
    out = jax.jit(attn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_flash_grads_match(seq_mesh):
    """Reverse-mode through P pallas_call hops + the LSE-weighted
    combine (exercises flash_attention_lse's g_lse backward path)."""
    q, k, v = _qkv(t=32)
    attn = make_ring_attention(seq_mesh, causal=True, impl="flash",
                               block_q=4, block_k=4)

    def loss_ring(q, k, v):
        return (attn(q, k, v) ** 2).sum()

    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    ge = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, ge):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_flash_lse_value_and_grad():
    """flash_attention_lse: LSE equals the dense logsumexp and its
    gradient path is correct (loss touches BOTH outputs)."""
    from fluxdistributed_tpu.ops.pallas_attention import flash_attention_lse

    q, k, v = _qkv(t=32, h=2, d=16)
    scale = 1.0 / np.sqrt(q.shape[-1])

    def dense_lse(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
        return jax.nn.logsumexp(s, axis=-1)

    out, lse = flash_attention_lse(q, k, v, False, 8, 8)
    np.testing.assert_allclose(
        np.asarray(lse), np.asarray(dense_lse(q, k, v)), rtol=2e-5, atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(dot_product_attention(q, k, v)), rtol=2e-5, atol=2e-5,
    )

    def loss_flash(q, k, v):
        o, l = flash_attention_lse(q, k, v, False, 8, 8)
        return (o ** 2).sum() + (jnp.sin(l) ** 2).sum()

    def loss_dense(q, k, v):
        o = dot_product_attention(q, k, v)
        return (o ** 2).sum() + (jnp.sin(dense_lse(q, k, v)) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_vit_with_ring_attention(data_seq_mesh):
    """ViT forward with sequence-parallel ring attention == reference ViT."""
    from fluxdistributed_tpu.models import vit_tiny

    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    m_ref = vit_tiny(num_classes=10, dtype=jnp.float32)
    variables = m_ref.init(jax.random.PRNGKey(0), x, train=False)
    attn = make_ring_attention(data_seq_mesh, batch_axis="data")
    m_ring = vit_tiny(num_classes=10, dtype=jnp.float32, attn_fn=attn)

    @jax.jit
    def fwd(variables, x):
        return m_ring.apply(variables, x, train=False)

    a = m_ref.apply(variables, x, train=False)
    b = fwd(variables, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("hkv", [2, 4, 8])
def test_ulysses_gqa_matches_single_device(seq_mesh, hkv):
    """Ulysses with grouped KV on the 8-way mesh: hkv in {2, 4} takes
    the expand-first fallback (hkv % 8 != 0) and hkv=8 is plain MHA —
    all must equal single-device GQA attention.  The GROUPED-comm branch
    is pinned separately by test_ulysses_gqa_grouped_comm_branch."""
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (2, 64, 8, 16), jnp.float32)
    k = jax.random.normal(ks[1], (2, 64, hkv, 16), jnp.float32)
    v = jax.random.normal(ks[2], (2, 64, hkv, 16), jnp.float32)
    ref = dot_product_attention(q, k, v, causal=True)
    attn = make_ulysses_attention(seq_mesh, causal=True)
    out = jax.jit(attn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ulysses_gqa_grouped_comm_branch(data_seq_mesh):
    """seq axis 4 with hkv=4 < h=8: the GROUPED all_to_all branch (hkv %
    axis == 0 while hkv != h) — KV re-shards at hkv heads and expands
    only after."""
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (2, 64, 8, 16), jnp.float32)
    k = jax.random.normal(ks[1], (2, 64, 4, 16), jnp.float32)
    v = jax.random.normal(ks[2], (2, 64, 4, 16), jnp.float32)
    ref = dot_product_attention(q, k, v, causal=True)
    attn = make_ulysses_attention(data_seq_mesh, batch_axis="data", causal=True)
    out = jax.jit(attn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_gqa_matches_single_device(seq_mesh):
    """Ring with grouped KV: ppermute traffic stays at hkv heads
    (expansion happens per hop, after the rotation)."""
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (2, 64, 8, 16), jnp.float32)
    k = jax.random.normal(ks[1], (2, 64, 2, 16), jnp.float32)
    v = jax.random.normal(ks[2], (2, 64, 2, 16), jnp.float32)
    ref = dot_product_attention(q, k, v, causal=True)
    attn = make_ring_attention(seq_mesh, causal=True)
    out = jax.jit(attn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
