"""Remote dataset sources: HTTP/gs:// drivers with local caching.

The reference registers the same dataset on a filesystem driver AND a
remote S3-backed driver (Data.toml:4-27).  These tests serve the
miniature ILSVRC fixture tree over a real local HTTP server and exercise
the full remote path: registry -> caching source -> metadata fetch ->
batch assembly (native or PIL decode) -> cache hits with the server gone.
"""

from __future__ import annotations

import http.server
import os
import threading

import numpy as np
import pytest

from fluxdistributed_tpu.data.sources import (
    FileSource, GCSSource, HTTPSource, fetch_artifact, fetch_checkpoint,
    make_source,
)

from test_data import imagenet_root  # noqa: F401  (module-scoped fixture)


@pytest.fixture()
def http_root(imagenet_root):  # noqa: F811
    """Serve the fixture tree over HTTP; yields (base_url, request_log)."""
    requests: list[str] = []

    class Handler(http.server.SimpleHTTPRequestHandler):
        def __init__(self, *a, **kw):
            super().__init__(*a, directory=imagenet_root, **kw)

        def log_message(self, *a):  # quiet
            pass

        def do_GET(self):
            requests.append(self.path)
            super().do_GET()

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}", requests
    finally:
        srv.shutdown()
        t.join(timeout=5)


def test_make_source_dispatch(tmp_path):
    assert isinstance(make_source(str(tmp_path)), FileSource)
    assert isinstance(make_source("http://x/y"), HTTPSource)
    s = make_source("gs://bucket/prefix", cache_dir=str(tmp_path))
    assert isinstance(s, GCSSource)
    assert s.base_url == "https://storage.googleapis.com/bucket/prefix"
    with pytest.raises(ValueError):
        GCSSource("s3://nope")


def test_http_source_fetch_and_cache(http_root, tmp_path):
    base, requests = http_root
    src = HTTPSource(base, cache_dir=str(tmp_path / "cache"))
    p = src.local_path("LOC_synset_mapping.txt")
    assert os.path.exists(p)
    assert "tench" in open(p).read()
    n = len(requests)
    p2 = src.local_path("LOC_synset_mapping.txt")
    assert p2 == p and len(requests) == n  # cache hit: no second request


def test_registry_remote_imagenet_end_to_end(http_root, imagenet_root, tmp_path):  # noqa: F811
    base, requests = http_root
    from fluxdistributed_tpu.data.registry import open_dataset, register_dataset

    register_dataset(
        "imagenet_http_test",
        "imagenet",
        path=base,
        cache_dir=str(tmp_path / "cache"),
    )
    ds = open_dataset("imagenet_http_test")
    imgs, labels = ds.batch(np.random.default_rng(0), 6)
    assert imgs.shape == (6, 224, 224, 3) and labels.shape == (6,)
    assert any("CLS-LOC" in r for r in requests)  # images actually remote

    # the identical draw through the filesystem driver must match exactly
    register_dataset("imagenet_local_ref", "imagenet", path=imagenet_root)
    ref = open_dataset("imagenet_local_ref")
    ref_imgs, ref_labels = ref.batch(np.random.default_rng(0), 6)
    np.testing.assert_array_equal(labels, ref_labels)
    np.testing.assert_allclose(imgs, ref_imgs, atol=1e-6)


def test_remote_cache_survives_server_shutdown(http_root, tmp_path):
    base, requests = http_root
    from fluxdistributed_tpu.data.registry import open_dataset, register_dataset
    from fluxdistributed_tpu.data.sources import HTTPSource

    register_dataset(
        "imagenet_http_test2",
        "imagenet",
        path=base,
        cache_dir=str(tmp_path / "cache"),
    )
    ds = open_dataset("imagenet_http_test2")
    idx = np.arange(4)
    first, _ = ds.batch(np.random.default_rng(1), 4, indices=idx)
    assert isinstance(ds.source, HTTPSource) and ds.root == base
    n_requests = len(requests)
    # warm cache must fully cover these files: the same batch re-assembles
    # bit-identically with no further HTTP traffic
    second, _ = ds.batch(np.random.default_rng(1), 4, indices=idx)
    np.testing.assert_array_equal(first, second)
    assert len(requests) == n_requests


@pytest.fixture()
def artifact_server(tmp_path):
    """Serve a tmp tree over HTTP; yields (base_url, root, request_log)."""
    import http.server
    import threading

    root = tmp_path / "remote"
    root.mkdir()
    requests: list[str] = []

    class Handler(http.server.SimpleHTTPRequestHandler):
        def __init__(self, *a, **kw):
            super().__init__(*a, directory=str(root), **kw)

        def log_message(self, *a):
            pass

        def do_GET(self):
            requests.append(self.path)
            super().do_GET()

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}", root, requests
    finally:
        srv.shutdown()
        t.join(timeout=5)


def test_fetch_artifact_local_passthrough(tmp_path):
    p = tmp_path / "weights.pt"
    p.write_bytes(b"x")
    assert fetch_artifact(str(p)) == str(p)
    assert fetch_checkpoint(str(tmp_path)) == str(tmp_path)


def test_fetch_artifact_remote_file_cached(artifact_server, tmp_path):
    base, root, requests = artifact_server
    (root / "model.pt").write_bytes(b"torchy bytes")
    local = fetch_artifact(f"{base}/model.pt", cache_dir=str(tmp_path / "c"))
    assert open(local, "rb").read() == b"torchy bytes"
    n = len(requests)
    again = fetch_artifact(f"{base}/model.pt", cache_dir=str(tmp_path / "c"))
    assert again == local and len(requests) == n  # cache hit


# slow tier: zip fetch + a second generate-CLI compile; the plain
# fetch/roundtrip paths stay fast
@pytest.mark.slow
def test_fetch_checkpoint_zip_roundtrip_via_generate_cli(
        artifact_server, tmp_path, capsys):
    """The full satellite path (reference: pluto.jl:52-124 fetches a
    trained model from job results): a trainer checkpoint dir zipped,
    served over HTTP, fetched + unpacked through the source cache, and
    sampled from by bin/generate.py --checkpoint <url>."""
    import shutil

    import jax

    from fluxdistributed_tpu import optim
    from fluxdistributed_tpu.models import lm_tiny
    from fluxdistributed_tpu.parallel import TrainState
    from fluxdistributed_tpu.train import save_checkpoint

    base, root, _ = artifact_server
    model = lm_tiny(vocab=256)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 2), np.int32), train=False
    )["params"]
    ck = tmp_path / "ck"
    save_checkpoint(TrainState.create(params, optim.descent(0.1)), str(ck), 0)
    shutil.make_archive(str(root / "ckpt"), "zip", str(ck))

    local = fetch_checkpoint(f"{base}/ckpt.zip", cache_dir=str(tmp_path / "c"))
    assert local != str(ck) and "ckpt" in local
    # idempotent: second resolve reuses the extracted tree
    assert fetch_checkpoint(f"{base}/ckpt.zip",
                            cache_dir=str(tmp_path / "c")) == local

    import pathlib
    import sys as _sys
    _sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "bin"))
    import generate as gen_cli
    import os

    os.environ["FDTPU_CACHE"] = str(tmp_path / "clicache")
    try:
        rc = gen_cli.main([
            "--model", "lm_tiny", "--checkpoint", f"{base}/ckpt.zip",
            "--prompt", "hi", "--length", "6",
        ])
    finally:
        del os.environ["FDTPU_CACHE"]
    assert rc == 0
    assert capsys.readouterr().out.startswith("hi")
