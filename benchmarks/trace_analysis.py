#!/usr/bin/env python
"""Trace-backed breakdown of the ResNet-50 train step.

Captures a ``jax.profiler`` trace of a few steady-state steps and parses
the xplane protobuf in-process (``jax.profiler.ProfileData`` — no
TensorBoard needed), aggregating device-op durations by fusion name.
This is the "where do the milliseconds go" tool for docs/benchmarks.md.

Usage: python benchmarks/trace_analysis.py [--steps 5] [--batch 256]
       [--model resnet50] [--top 30] [--platform cpu]
"""

from __future__ import annotations

import argparse
import collections
import glob
import os
import re
import tempfile


def capture(args) -> str:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import fluxdistributed_tpu as fd
    from fluxdistributed_tpu import optim, sharding
    from fluxdistributed_tpu import models as models_lib
    from fluxdistributed_tpu.parallel import TrainState, make_train_step
    from fluxdistributed_tpu.parallel.dp import flax_loss_fn

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    mesh = fd.data_mesh()
    model = getattr(models_lib, args.model)(
        num_classes=1000, space_to_depth=args.s2d
    )
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (args.batch, args.size, args.size, 3)).astype(np.float32)
    if args.s2d:
        x = np.ascontiguousarray(models_lib.space_to_depth(x))
    y = rng.integers(0, 1000, args.batch)
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=True)
    params = variables["params"]
    mstate = {k: v for k, v in variables.items() if k != "params"}
    loss_fn = flax_loss_fn(model, fd.logitcrossentropy)
    opt = optim.momentum(0.1, 0.9)
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    state = TrainState.create(
        sharding.replicate(params, mesh), opt,
        model_state=sharding.replicate(mstate, mesh),
    )
    b = sharding.shard_batch(
        {"image": x.astype(jnp.bfloat16),
         "label": np.asarray(fd.onehot(y, 1000))}, mesh
    )
    # compile + warm
    for _ in range(2):
        state, m = step(state, b)
    jax.block_until_ready(m["loss"])

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="fdtpu_trace_")
    jax.profiler.start_trace(trace_dir)
    for _ in range(args.steps):
        state, m = step(state, b)
    jax.block_until_ready(m["loss"])
    jax.profiler.stop_trace()
    return trace_dir


_CLASS_PATTERNS = [
    ("conv", re.compile(r"conv|%convolution", re.I)),
    ("matmul", re.compile(r"dot|matmul", re.I)),
    ("allreduce/collective", re.compile(r"all-reduce|all-gather|collective|reduce-scatter", re.I)),
    ("batchnorm/elementwise", re.compile(r"fusion|add|multiply|subtract|divide|rsqrt|select", re.I)),
    ("reduce", re.compile(r"reduce", re.I)),
    ("copy/transpose", re.compile(r"copy|transpose|bitcast|reshape", re.I)),
]


def classify(name: str) -> str:
    for label, pat in _CLASS_PATTERNS:
        if pat.search(name):
            return label
    return "other"


def analyze(trace_dir: str, top: int):
    from jax.profiler import ProfileData

    paths = sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                             recursive=True))
    if not paths:
        raise SystemExit(f"no xplane.pb under {trace_dir}")
    pd = ProfileData.from_file(paths[-1])

    # pick accelerator device planes; on CPU there is no device plane, so
    # fall back to the host plane and SAY SO — host traces mix Python
    # frames in with XLA thunks and are not a device-op breakdown
    best = []
    for plane in pd.planes:
        pname = plane.name or ""
        if any(s in pname.lower() for s in ("tpu", "gpu", "device", "/xla:")):
            best.append(plane)
    host_fallback = not best
    if host_fallback:
        planes = [p for p in pd.planes if "cpu" in (p.name or "").lower()]
        best = planes[:1]
        if not best:
            raise SystemExit(
                f"no device plane in trace; planes = {[p.name for p in pd.planes]}"
            )
        print(
            "WARNING: no accelerator plane found — analyzing the HOST plane "
            "(includes Python/runtime frames; op classes are approximate). "
            "Run on TPU for a real device breakdown.\n"
        )

    durs: dict[str, float] = collections.defaultdict(float)
    counts: dict[str, int] = collections.defaultdict(int)
    for plane in best:
        for line in plane.lines:
            for ev in line.events:
                d = ev.duration_ns
                if d is None:
                    continue
                durs[ev.name] += d / 1e6  # ms
                counts[ev.name] += 1

    total = sum(durs.values())
    print(f"trace: {paths[-1]}")
    print(f"planes analyzed: {[p.name for p in best]}")
    print(f"total device-op time: {total:.1f} ms (all steps, incl. overlap)\n")

    by_class: dict[str, float] = collections.defaultdict(float)
    for name, ms in durs.items():
        by_class[classify(name)] += ms
    print("by op class:")
    for label, ms in sorted(by_class.items(), key=lambda kv: -kv[1]):
        print(f"  {label:26s} {ms:9.1f} ms  ({100 * ms / max(total, 1e-9):5.1f}%)")

    print(f"\ntop {top} ops by total time:")
    for name, ms in sorted(durs.items(), key=lambda kv: -kv[1])[:top]:
        print(f"  {ms:9.2f} ms  x{counts[name]:<4d} {name[:110]}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--s2d", action="store_true",
                    help="trace the space_to_depth-stem model instead")
    ap.add_argument("--trace-dir", default=None)
    ap.add_argument("--analyze-only", default=None,
                    help="skip capture; analyze this trace dir")
    args = ap.parse_args()
    trace_dir = args.analyze_only or capture(args)
    analyze(trace_dir, args.top)


if __name__ == "__main__":
    main()
