"""Fully-sharded data parallelism (ZeRO-3 style) via GSPMD annotations.

Scope beyond the reference: its DDP keeps a full model + optimizer copy
per device (replica replication, src/ddp_tasks.jl:273-276), so the
largest trainable model is bounded by ONE device's memory.  FSDP removes
that bound the TPU-native way — not by hand-written bucketed all-gathers
(the torch FSDP/DeepSpeed approach), but by *annotation*: every
parameter and optimizer-state leaf is sharded across the ``data`` axis,
and the train step is the UNCHANGED DP step (``dp.make_train_step``)
compiled with those shardings.  XLA's SPMD partitioner then inserts

* an all-gather per layer when the forward/backward needs the full
  parameter (overlapped with compute by the latency-hiding scheduler),
* a reduce-scatter for the gradient at the sharded optimizer update
  (replacing DP's all-reduce, at half the bytes on the wire),

which is exactly the ZeRO-3 communication schedule, derived by the
compiler instead of scheduled by hand.

Per-device memory for params + optimizer state drops ~N× on an N-way
mesh (verified by ``tests/test_fsdp.py`` via ``addressable_shards``);
numerics match the DP step's up to float reduction order — the
annotations change where sums happen (reduce-scatter vs all-reduce),
not the math, and ``tests/test_fsdp.py`` asserts agreement to ~1e-5
over multiple optimizer steps.

Usage::

    specs  = fsdp_specs(state, mesh)              # TrainState of PartitionSpecs
    state  = shard_state(state, specs, mesh)      # place shards
    step   = make_train_step_fsdp(loss_fn, opt, mesh, specs)

CPU-emulation caveat: on a ``--xla_force_host_platform_device_count``
fake mesh, XLA:CPU runs each device as a thread-pool thread and its
in-process cross-module collectives (the per-layer all-gathers this
schedule introduces) can deadlock when several *donated* steps are in
flight at once — threads from different executions join the same
rendezvous.  Synchronize per step (``jax.block_until_ready``) or pass
``donate=False`` when driving FSDP on the CPU mesh; real TPUs execute
programs in per-device FIFO order and are unaffected.
"""

from __future__ import annotations

from typing import Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import mesh as mesh_lib
from ..optim import Optimizer
from . import dp

__all__ = [
    "fsdp_leaf_spec",
    "fsdp_specs",
    "hybrid_fsdp_tp_specs",
    "shard_state",
    "make_train_step_fsdp",
    "make_eval_step_fsdp",
]

# Leaves smaller than this stay replicated: sharding a 64-float BatchNorm
# bias saves nothing and costs a latency-bound collective per use.
MIN_SHARD_ELEMS = 2**11


def fsdp_leaf_spec(
    shape, axis: str = mesh_lib.DATA_AXIS, nshards: int = 1,
    min_size: int = MIN_SHARD_ELEMS, base: P | None = None,
) -> P:
    """PartitionSpec for one leaf, chosen from its shape alone.

    Shards the largest dimension divisible by ``nshards`` (ties broken
    toward the trailing dim — for conv HWIO / dense (in, out) kernels
    that is the output-features dim, giving contiguous lanes-friendly
    shards).  Leaves with fewer than ``min_size`` elements, or no
    divisible dim, stay replicated.

    ``base`` composes with an existing spec (the hybrid FSDP×TP path):
    only dims the base leaves unsharded are candidates, and the base's
    entries are preserved in the result.

    The rule is a pure function of shape (and base), so a parameter and
    its optimizer-state slots (momentum/Adam moments have the param's
    shape) always agree — the property that lets one spec tree cover the
    whole ``TrainState``.
    """
    entries = (
        list(base) + [None] * (len(shape) - len(base))
        if base is not None
        else [None] * len(shape)
    )
    keep = P(*entries) if base is not None else P()
    if not shape or int(np.prod(shape)) < min_size:
        return keep
    best = None  # (extent, dim)
    for d, extent in enumerate(shape):
        if entries[d] is None and extent % nshards == 0 and extent >= nshards:
            if best is None or extent >= best[0]:
                best = (extent, d)
    if best is None:
        return keep
    entries[best[1]] = axis
    return P(*entries)


def fsdp_specs(
    state: dp.TrainState,
    mesh: Mesh,
    axis: str = mesh_lib.DATA_AXIS,
    min_size: int = MIN_SHARD_ELEMS,
) -> dp.TrainState:
    """A ``TrainState`` of PartitionSpecs: params and optimizer state
    sharded by :func:`fsdp_leaf_spec`; mutable model state (BatchNorm
    running stats — small, and updated from *activation* statistics, not
    gradients) and the step counter replicated."""
    n = mesh.shape[axis]

    def leaf(x):
        return fsdp_leaf_spec(np.shape(x), axis, n, min_size)

    return dp.TrainState(
        params=jax.tree.map(leaf, state.params),
        opt_state=jax.tree.map(leaf, state.opt_state),
        model_state=jax.tree.map(lambda _: P(), state.model_state),
        step=P(),
    )


def hybrid_fsdp_tp_specs(
    params,
    mesh: Mesh,
    tp_rules: Callable,
    data_axis: str = mesh_lib.DATA_AXIS,
    min_size: int = MIN_SHARD_ELEMS,
):
    """2-D sharding on a ``(data, model)`` mesh — the standard large-model
    TPU recipe ("How to Scale Your Model" lineage): tensor parallelism
    per ``tp_rules`` (e.g. ``tp.lm_tp_rules()`` — the model axis name is
    the rules', not this function's, decision) PLUS FSDP over
    ``data_axis`` on each leaf's largest still-unsharded dim.  Per-device
    param/opt memory ≈ size / (|data|·|model|); XLA derives the combined
    all-gather / reduce-scatter schedule from the annotations as usual.

    Returns a PartitionSpec tree for ``params`` (feed through
    ``tp.state_specs`` + ``sharding.make_shardings``).
    """
    from .tp import param_specs

    n_data = mesh.shape[data_axis]
    tp_specs = param_specs(params, tp_rules)
    return jax.tree.map(
        lambda spec, leaf: fsdp_leaf_spec(
            np.shape(leaf), data_axis, n_data, min_size, base=spec
        ),
        tp_specs, params, is_leaf=lambda x: isinstance(x, P),
    )


def _shardings(specs: dp.TrainState, mesh: Mesh) -> dp.TrainState:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_state(state: dp.TrainState, specs: dp.TrainState, mesh: Mesh) -> dp.TrainState:
    """Place each state leaf according to its spec (shards distributed
    across the mesh; replicated leaves copied everywhere)."""
    from ..sharding import unaliased

    return jax.tree.map(
        lambda x, s: jax.device_put(unaliased(x), s), state, _shardings(specs, mesh)
    )


def make_train_step_fsdp(
    loss_fn: Callable,
    optimizer: Optimizer,
    mesh: Mesh,
    specs: dp.TrainState,
    axis: str = mesh_lib.DATA_AXIS,
    donate: bool = True,
    accum_steps: int = 1,
    seed: int = 0,
):
    """The DP train step compiled with fully-sharded state.

    Identical math to ``dp.make_train_step`` (same loss, same implicit
    gradient reduction, same optimizer) — only the state's shardings
    differ, so the compiler emits the ZeRO-3 schedule described in the
    module docstring.  ``batch`` stays sharded on ``axis`` exactly as in
    DP.
    """
    return dp.make_train_step(
        loss_fn, optimizer, mesh,
        axis=axis, donate=donate, accum_steps=accum_steps, seed=seed,
        state_shardings=_shardings(specs, mesh),
    )


def make_eval_step_fsdp(
    loss_fn: Callable,
    mesh: Mesh,
    specs: dp.TrainState,
    axis: str = mesh_lib.DATA_AXIS,
    topk: tuple = (1, 5, 10),
):
    """Eval pass accepting the FSDP-sharded state directly (no gather to
    host, no resharding round-trip)."""
    return dp.make_eval_step(
        loss_fn, mesh, axis=axis, topk=topk,
        state_shardings=_shardings(specs, mesh),
    )
