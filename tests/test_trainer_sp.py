"""Sequence/context parallelism as a trainer mode.

``spmd="sp"`` rides the plain jit path with replicated params; the
model's mesh-bound ring attention shards the sequence dimension over
the ``seq`` axis inside its own shard_map while the batch stays
data-sharded.  The trainer's job is mesh validation — everything else
is the standard surface.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu import mesh as mesh_lib, optim
from fluxdistributed_tpu.data import SyntheticTextDataset
from fluxdistributed_tpu.models import lm_loss_fn
from fluxdistributed_tpu.models.transformer_lm import TransformerLM
from fluxdistributed_tpu.parallel import make_ring_attention
from fluxdistributed_tpu.train import prepare_training

VOCAB = 32


def test_sp_trainer_mode_trains(tmp_path):
    mesh = mesh_lib.make_mesh({"data": 2, "seq": 4})
    model = TransformerLM(
        vocab=VOCAB, dim=32, depth=2, num_heads=2, mlp_dim=64,
        dtype=jnp.float32, dropout=0.0,
        attn_fn=make_ring_attention(mesh, batch_axis="data", causal=True),
    )
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=32, peak=0.95)
    task = prepare_training(
        model, ds, optim.adam(3e-3),
        mesh=mesh, batch_size=16, cycles=30, spmd="sp",
        loss_fn=lm_loss_fn(model), topk=(),
        val_dataset=ds, val_samples=8,
    )
    losses = []
    for batch in task.loader:
        task.state, m = task.step_fn(task.state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    loss, _ = task.eval_fn(task.state, task.val_batch)
    assert np.isfinite(float(loss))


def test_sp_mode_rejects_missing_seq_axis():
    model = TransformerLM(
        vocab=VOCAB, dim=32, depth=2, num_heads=2, mlp_dim=64,
        dtype=jnp.float32, dropout=0.0,
    )
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=32)
    with pytest.raises(ValueError, match="seq"):
        prepare_training(
            model, ds, optim.adam(1e-3),
            mesh=mesh_lib.data_mesh(8), batch_size=16, spmd="sp",
            loss_fn=lm_loss_fn(model), topk=(),
        )


def test_unknown_spmd_rejected():
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=32)
    model = TransformerLM(
        vocab=VOCAB, dim=32, depth=2, num_heads=2, mlp_dim=64,
        dtype=jnp.float32, dropout=0.0,
    )
    with pytest.raises(ValueError, match="unknown spmd"):
        prepare_training(
            model, ds, optim.adam(1e-3),
            mesh=mesh_lib.data_mesh(8), batch_size=16, spmd="typo",
            loss_fn=lm_loss_fn(model), topk=(),
        )
