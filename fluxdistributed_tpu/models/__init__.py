from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152
from .simple import SimpleCNN, MLP

__all__ = [
    "ResNet",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "resnet152",
    "SimpleCNN",
    "MLP",
]
