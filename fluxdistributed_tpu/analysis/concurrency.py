"""Layer-3 concurrency rules: race/deadlock hazards in the host-side
orchestration, detectable from source alone.

The serve router/scheduler, obs watchdog/flight/reqtrace, bin/supervise
and the prefetch loader form a genuinely multi-threaded system, and its
worst historical bugs were all races caught by hand in review (the
double-locked tracer path, unlocked read-then-increment fault indices).
This layer makes that review mechanical:

========  ======================================================
FDT301    lock-coverage inference — an attribute a class protects with
          ``with self._lock:`` somewhere but WRITES outside any lock
          elsewhere.  Read-modify-write (``+=``, read-then-assign,
          ``.append``/``.update`` mutation) is an error; a plain
          flag-store is a warning
FDT302    lock-order graph across classes/modules with cycle
          detection — an A→B lock edge in one path and B→A in another
          is a potential deadlock; so is re-acquiring a non-reentrant
          ``Lock`` through a same-class call chain
FDT303    blocking call while holding a lock — HTTP requests,
          ``subprocess`` execution, or ``join``/``wait``/``.get()``/
          ``time.sleep`` WITHOUT a timeout inside a lock region
          serializes every other thread behind an unbounded wait
FDT304    thread-lifecycle audit — a non-daemon Thread/Timer that no
          code path ever joins (leaks and blocks interpreter exit);
          a class registering scrape-time callback gauges
          (``set_function``) with no close/stop path that unregisters
          them (pins the object forever on shared registries)
FDT305    a module global mutated from a thread-target function with
          no lock held
========  ======================================================

Like layer 1 the engine is stdlib-``ast`` only (milliseconds, no jax)
and errs toward *precision*: coverage is inferred per class from the
locks the class itself constructs, method-call edges resolve only
unambiguous names, and driver-thread-only state that is never
lock-covered is deliberately out of scope.  Findings ride the same
:mod:`analysis.findings` baseline workflow as FDT1xx/FDT2xx; the rules
live in their own :data:`CONC_RULES` registry (the FDT1xx registry is
byte-pinned by tests).

The dynamic counterpart is :mod:`analysis.schedules` — a deterministic
lock-interposition harness that *reproduces* the interleavings these
rules predict.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import (Callable, Dict, Iterable, List, Optional, Sequence,
                    Set, Tuple)

from .findings import Finding

__all__ = [
    "ConcRule",
    "CONC_RULES",
    "conc_rule",
    "run_concurrency_checks",
]

#: constructors (leaf name) that make an attribute a *lock* — the
#: region marker FDT301/302/303 coverage keys on
_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition",
               "Semaphore": "Semaphore", "BoundedSemaphore": "Semaphore"}

#: methods whose writes are construction, not racing mutation
_INIT_METHODS = {"__init__", "__post_init__", "__new__", "__del__"}

#: container-mutating method names — a call through a covered attribute
#: is a read-modify-write of the shared object
_MUTATORS = {"append", "extend", "appendleft", "pop", "popleft", "remove",
             "add", "discard", "update", "clear", "insert", "setdefault",
             "sort", "reverse", "popitem"}

#: dotted-call prefixes that are *always* blocking (no timeout can help)
_BLOCKING_PREFIXES = ("requests.", "urllib.request.", "subprocess.")
_BLOCKING_LEAVES_ALWAYS = {"urlopen", "check_output", "check_call",
                           "run", "call", "communicate"}
#: leaf calls blocking only when no timeout is passed: ``q.get()``,
#: ``t.join()``, ``ev.wait()``, ``time.sleep(...)`` (sleep's duration
#: arg IS the bound, so bare ``sleep`` with args still counts as
#: bounded only when the literal is small — we flag sleep regardless:
#: any deliberate sleep under a lock serializes the system)
_BLOCKING_LEAVES_TIMEOUT = {"get", "join", "wait", "acquire"}

#: method names too generic to resolve cross-class call edges through
#: (``.get()`` is every dict, ``.close()`` is every resource, ...)
_AMBIGUOUS_METHODS = {"get", "set", "put", "close", "open", "stop",
                      "start", "run", "join", "wait", "update", "clear",
                      "pop", "append", "items", "keys", "values", "read",
                      "write", "send", "record", "event"}


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _self_attr(node: ast.AST) -> Optional[str]:
    """``X`` for a ``self.X`` attribute node, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _lock_ctor_kind(node: ast.AST) -> Optional[str]:
    """``"Lock"``/``"RLock"``/... when ``node`` is a
    ``threading.Lock()``-style constructor call."""
    if isinstance(node, ast.Call):
        leaf = _dotted(node.func).split(".")[-1]
        return _LOCK_CTORS.get(leaf)
    return None


# -- per-method walk -------------------------------------------------------


@dataclasses.dataclass
class _Access:
    attr: str
    kind: str  # read | assign | aug | mutcall | substore
    node: ast.AST
    held: Tuple[str, ...]
    in_nested: bool  # inside a nested def (closure/thread target body)


@dataclasses.dataclass
class _CallSite:
    callee: str  # dotted call target ("self._emit", "rep.probe", ...)
    node: ast.Call
    held: Tuple[str, ...]
    has_timeout: bool


@dataclasses.dataclass
class _MethodModel:
    name: str
    node: ast.AST
    acquires: Set[str] = dataclasses.field(default_factory=set)
    accesses: List[_Access] = dataclasses.field(default_factory=list)
    calls: List[_CallSite] = dataclasses.field(default_factory=list)
    #: non-empty once propagation decides every call site of this
    #: (private, lock-free) method already holds these locks
    wholly_locked: Tuple[str, ...] = ()


def _with_self_locks(node: ast.With, lock_attrs: Set[str]) -> List[str]:
    """Lock attrs a ``with`` statement acquires (``with self._lock:``,
    ``with self._lock, open(...):``)."""
    out = []
    for item in node.items:
        attr = _self_attr(item.context_expr)
        if attr in lock_attrs:
            out.append(attr)
    return out


def _call_has_timeout(call: ast.Call) -> bool:
    if call.args:
        return True  # join(5) / wait(0.05) / get(key, default)
    return any(k.arg == "timeout" for k in call.keywords)


def _walk_method(node: ast.AST, lock_attrs: Set[str]) -> _MethodModel:
    mm = _MethodModel(name=node.name, node=node)

    def visit(n: ast.AST, held: Tuple[str, ...], nested: bool) -> None:
        if isinstance(n, ast.With):
            got = _with_self_locks(n, lock_attrs)
            mm.acquires.update(got)
            inner = held + tuple(a for a in got if a not in held)
            for item in n.items:
                visit(item.context_expr, held, nested)
                if item.optional_vars is not None:
                    visit(item.optional_vars, held, nested)
            for child in n.body:
                visit(child, inner, nested)
            return
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n is not node:
            # a nested def's BODY does not run under the enclosing
            # with — it is typically a thread target or callback, the
            # least-synchronized code in the class
            for child in ast.iter_child_nodes(n):
                visit(child, (), True)
            return
        if isinstance(n, ast.Lambda):
            visit(n.body, (), True)
            return
        if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (n.targets if isinstance(n, ast.Assign)
                       else [n.target])
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    kind = "aug" if isinstance(n, ast.AugAssign) else "assign"
                    mm.accesses.append(_Access(attr, kind, n, held, nested))
                elif (isinstance(t, ast.Subscript)):
                    base = _self_attr(t.value)
                    if base is not None:
                        mm.accesses.append(
                            _Access(base, "substore", n, held, nested))
                    else:
                        visit(t, held, nested)
                else:
                    visit(t, held, nested)
            if n.value is not None:
                visit(n.value, held, nested)
            return
        if isinstance(n, ast.Call):
            # chained receivers (`registry.gauge(...).set_function(...)`)
            # break the dotted chain — fall back to the attribute leaf
            # so method-name-keyed rules still see the call
            d = _dotted(n.func) or (
                n.func.attr if isinstance(n.func, ast.Attribute) else "")
            if d:
                mm.calls.append(_CallSite(d, n, held, _call_has_timeout(n)))
            if isinstance(n.func, ast.Attribute) and n.func.attr in _MUTATORS:
                base = _self_attr(n.func.value)
                if base is not None:
                    mm.accesses.append(
                        _Access(base, "mutcall", n, held, nested))
            for child in ast.iter_child_nodes(n):
                visit(child, held, nested)
            return
        if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load):
            attr = _self_attr(n)
            if attr is not None:
                mm.accesses.append(_Access(attr, "read", n, held, nested))
            visit(n.value, held, nested)
            return
        for child in ast.iter_child_nodes(n):
            visit(child, held, nested)

    for child in ast.iter_child_nodes(node):
        visit(child, (), False)
    return mm


# -- per-class / per-module models ----------------------------------------


@dataclasses.dataclass
class _ClassModel:
    name: str
    node: ast.ClassDef
    relpath: str
    lock_attrs: Dict[str, str]  # attr -> ctor kind (Lock/RLock/...)
    methods: Dict[str, _MethodModel]
    defines_set_function: bool = False

    def effective_held(self, mm: _MethodModel,
                       held: Tuple[str, ...]) -> Tuple[str, ...]:
        return held if held else mm.wholly_locked


@dataclasses.dataclass
class _ModuleModel:
    relpath: str
    tree: ast.Module
    classes: List[_ClassModel]
    module_locks: Set[str]
    module_globals: Set[str]
    thread_targets: Set[str]
    functions: Dict[str, List[ast.AST]]  # every def anywhere, by name
    thread_sites: List[Tuple[ast.Call, Optional[str], Optional[ast.AST]]]
    # (call node, enclosing class name, enclosing def node)


def _build_class(node: ast.ClassDef, relpath: str) -> _ClassModel:
    lock_attrs: Dict[str, str] = {}
    defines_sf = False
    method_nodes = [n for n in node.body
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for m in method_nodes:
        if m.name == "set_function":
            defines_sf = True
        for n in ast.walk(m):
            if isinstance(n, ast.Assign):
                kind = _lock_ctor_kind(n.value)
                if kind:
                    for t in n.targets:
                        attr = _self_attr(t)
                        if attr is not None:
                            lock_attrs[attr] = kind
    methods = {m.name: _walk_method(m, set(lock_attrs)) for m in method_nodes}
    cls = _ClassModel(node.name, node, relpath, lock_attrs, methods,
                      defines_sf)
    _propagate_wholly_locked(cls)
    return cls


def _propagate_wholly_locked(cls: _ClassModel) -> None:
    """A private lock-free method whose every in-class call site holds a
    lock runs with that lock held by contract (the repo's documented
    "lock held by caller" idiom) — treat its body as one lock region."""
    sites: Dict[str, List[Tuple[_MethodModel, Tuple[str, ...]]]] = {}
    for mm in cls.methods.values():
        for call in mm.calls:
            if call.callee.startswith("self."):
                parts = call.callee.split(".")
                if len(parts) == 2:
                    sites.setdefault(parts[1], []).append((mm, call.held))
    changed = True
    while changed:
        changed = False
        for name, mm in cls.methods.items():
            if (mm.wholly_locked or not name.startswith("_")
                    or name in _INIT_METHODS or mm.acquires):
                continue
            ss = sites.get(name)
            if not ss:
                continue
            held_sets = []
            ok = True
            for caller, held in ss:
                eff = held if held else caller.wholly_locked
                if not eff:
                    ok = False
                    break
                held_sets.append(eff)
            if ok:
                mm.wholly_locked = held_sets[0]
                changed = True


def _module_level_locks_and_globals(
        tree: ast.Module) -> Tuple[Set[str], Set[str]]:
    locks: Set[str] = set()
    mutables: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if _lock_ctor_kind(node.value):
                locks.add(name)
            elif isinstance(node.value, (ast.Dict, ast.List, ast.Set,
                                         ast.ListComp, ast.DictComp,
                                         ast.SetComp, ast.Call)):
                mutables.add(name)
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            if _lock_ctor_kind(node.value):
                locks.add(node.target.id)
            elif isinstance(node.value, (ast.Dict, ast.List, ast.Set,
                                         ast.Call)):
                mutables.add(node.target.id)
    return locks, mutables


def _is_thread_ctor(call: ast.Call) -> Optional[str]:
    leaf = _dotted(call.func).split(".")[-1]
    return leaf if leaf in ("Thread", "Timer") else None


def _build_module(path: str, relpath: str,
                  tree: ast.Module) -> _ModuleModel:
    classes = [_build_class(n, relpath) for n in ast.walk(tree)
               if isinstance(n, ast.ClassDef)]
    module_locks, module_globals = _module_level_locks_and_globals(tree)

    functions: Dict[str, List[ast.AST]] = {}
    thread_targets: Set[str] = set()
    thread_sites: List[Tuple[ast.Call, Optional[str], Optional[ast.AST]]] = []

    # one pass with an explicit (class, function) scope stack
    def scan(node: ast.AST, cls: Optional[str],
             fn: Optional[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                scan(child, child.name, fn)
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                functions.setdefault(child.name, []).append(child)
                scan(child, cls, child)
                continue
            if isinstance(child, ast.Call) and _is_thread_ctor(child):
                thread_sites.append((child, cls, fn))
                for k in child.keywords:
                    if k.arg == "target":
                        d = _dotted(k.value)
                        if d:
                            thread_targets.add(d.split(".")[-1])
            scan(child, cls, fn)

    scan(tree, None, None)
    return _ModuleModel(relpath, tree, classes, module_locks,
                        module_globals, thread_targets, functions,
                        thread_sites)


class CorpusContext:
    """Every scanned module, parsed and modeled — FDT302's lock-order
    graph is global across modules, so unlike layer 1 the concurrency
    rules see the whole corpus at once."""

    def __init__(self, modules: Sequence[_ModuleModel]):
        self.modules = list(modules)
        #: method name -> [(class, method model)] for every class method
        #: that acquires at least one of its own locks — the cross-class
        #: edge resolution index
        self.locking_methods: Dict[str, List[Tuple[_ClassModel,
                                                   _MethodModel]]] = {}
        for mod in self.modules:
            for cls in mod.classes:
                if not cls.lock_attrs:
                    continue
                for name, mm in cls.methods.items():
                    if mm.acquires:
                        self.locking_methods.setdefault(name, []).append(
                            (cls, mm))


# -- rule registry ---------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ConcRule:
    id: str
    name: str
    severity: str
    description: str
    hint: str
    check: Callable[[CorpusContext], Iterable[Finding]]


CONC_RULES: List[ConcRule] = []


def conc_rule(id: str, name: str, severity: str, description: str,
              hint: str):
    """Register a concurrency rule.  ``check(corpus)`` yields findings
    over the whole scanned corpus (FDT302 is inherently cross-module;
    the others iterate per module for locality)."""

    def deco(fn):
        CONC_RULES.append(ConcRule(id, name, severity, description,
                                   hint, fn))
        return fn

    return deco


def _rule_by_id(rid: str) -> ConcRule:
    return next(r for r in CONC_RULES if r.id == rid)


def _finding(rule: ConcRule, relpath: str, node: ast.AST, message: str,
             detail: str, severity: Optional[str] = None,
             hint: Optional[str] = None) -> Finding:
    return Finding(
        rule=rule.id,
        severity=severity or rule.severity,
        file=relpath,
        line=getattr(node, "lineno", 0),
        message=message,
        hint=hint if hint is not None else rule.hint,
        detail=detail,
    )


# -- FDT301: lock-coverage inference --------------------------------------


@conc_rule(
    "FDT301", "lock-coverage", "warning",
    "An attribute the class accesses under its own lock is written "
    "elsewhere with NO lock held — two threads can interleave around "
    "the unlocked write.",
    "take the same `with self._lock:` around the unlocked write (keep "
    "callbacks/tracing OUTSIDE the region), or stop locking the "
    "attribute anywhere if it is genuinely single-thread state")
def _check_lock_coverage(corpus: CorpusContext) -> Iterable[Finding]:
    rule = _rule_by_id("FDT301")
    for mod in corpus.modules:
        for cls in mod.classes:
            if not cls.lock_attrs:
                continue
            # coverage: attr -> a lock it was accessed under
            covered: Dict[str, str] = {}
            for mm in cls.methods.values():
                for a in mm.accesses:
                    eff = cls.effective_held(mm, a.held)
                    if eff and a.attr not in covered:
                        covered[a.attr] = eff[0]
            if not covered:
                continue
            reported: Set[Tuple[str, str]] = set()
            for mm in cls.methods.values():
                if mm.name in _INIT_METHODS:
                    continue
                unlocked_reads = {
                    a.attr for a in mm.accesses
                    if a.kind == "read"
                    and not cls.effective_held(mm, a.held)}
                for a in mm.accesses:
                    if a.kind == "read" or a.attr not in covered:
                        continue
                    if a.attr in cls.lock_attrs:
                        continue
                    if cls.effective_held(mm, a.held):
                        continue
                    key = (mm.name, a.attr)
                    if key in reported:
                        continue
                    reported.add(key)
                    lock = covered[a.attr]
                    rmw = (a.kind in ("aug", "mutcall", "substore")
                           or a.attr in unlocked_reads)
                    sev = "error" if rmw else None
                    what = {"aug": "read-modify-written (augmented "
                                   "assignment)",
                            "mutcall": "mutated in place",
                            "substore": "mutated by subscript store",
                            "assign": ("read-then-assigned"
                                       if a.attr in unlocked_reads
                                       else "written")}[a.kind]
                    yield _finding(
                        rule, cls.relpath, a.node,
                        f"`self.{a.attr}` is lock-covered (accessed "
                        f"under `self.{lock}`) but {what} without the "
                        f"lock in `{cls.name}.{mm.name}`",
                        detail=f"{cls.name}.{mm.name}.{a.attr}",
                        severity=sev)


# -- FDT302: lock-order cycles --------------------------------------------


@conc_rule(
    "FDT302", "lock-order-cycle", "error",
    "The cross-class lock-acquisition graph has a cycle — two threads "
    "taking the locks in opposite order deadlock.",
    "establish one global acquisition order (document it), or narrow a "
    "lock region so the nested acquisition happens after release — the "
    "registry's copy-under-lock/render-after-release pattern")
def _check_lock_order(corpus: CorpusContext) -> Iterable[Finding]:
    rule = _rule_by_id("FDT302")
    # nodes "Class.lockattr"; edges (holder -> acquired) with a witness
    edges: Dict[str, Dict[str, Tuple[str, ast.AST]]] = {}

    def add_edge(src: str, dst: str, relpath: str, node: ast.AST) -> None:
        edges.setdefault(src, {}).setdefault(dst, (relpath, node))

    for mod in corpus.modules:
        for cls in mod.classes:
            if not cls.lock_attrs:
                continue
            for mm in cls.methods.values():
                for call in mm.calls:
                    held = cls.effective_held(mm, call.held)
                    if not held:
                        continue
                    parts = call.callee.split(".")
                    leaf = parts[-1]
                    if parts[0] == "self" and len(parts) == 2:
                        callee = cls.methods.get(leaf)
                        if callee is None:
                            continue
                        for lk in callee.acquires:
                            for src in held:
                                if lk == src:
                                    # re-entry through a non-reentrant
                                    # Lock is an immediate self-deadlock
                                    if cls.lock_attrs.get(lk) == "Lock":
                                        yield _finding(
                                            rule, cls.relpath, call.node,
                                            f"`{cls.name}.{mm.name}` "
                                            f"holds `self.{lk}` (a "
                                            f"non-reentrant Lock) and "
                                            f"calls `self.{leaf}` which "
                                            f"acquires it again",
                                            detail=(f"{cls.name}.{lk}"
                                                    f"->{cls.name}.{lk}"))
                                else:
                                    add_edge(f"{cls.name}.{src}",
                                             f"{cls.name}.{lk}",
                                             cls.relpath, call.node)
                        continue
                    if leaf in _AMBIGUOUS_METHODS:
                        continue
                    targets = corpus.locking_methods.get(leaf, [])
                    # resolve only an unambiguous method name — one
                    # lock-acquiring class in the whole corpus defines it
                    resolved = {id(c.node): (c, m) for c, m in targets}
                    if len(resolved) != 1:
                        continue
                    (tcls, tmm), = resolved.values()
                    if tcls is cls:
                        continue
                    for lk in tmm.acquires:
                        for src in held:
                            add_edge(f"{cls.name}.{src}",
                                     f"{tcls.name}.{lk}",
                                     cls.relpath, call.node)

    # cycle detection: DFS with colors; report each cycle once,
    # canonicalized by its sorted node set
    seen_cycles: Set[Tuple[str, ...]] = set()
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(node: str) -> Iterable[Finding]:
        color[node] = GRAY
        stack.append(node)
        for nxt, (relpath, witness) in edges.get(node, {}).items():
            c = color.get(nxt, WHITE)
            if c == GRAY:
                cyc = tuple(stack[stack.index(nxt):])
                key = tuple(sorted(cyc))
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    path = " -> ".join(cyc + (nxt,))
                    yield _finding(
                        rule, relpath, witness,
                        f"lock-order cycle: {path} — threads taking "
                        f"these locks in opposite order deadlock",
                        detail="->".join(key))
            elif c == WHITE:
                yield from dfs(nxt)
        stack.pop()
        color[node] = BLACK

    for node in sorted(edges):
        if color.get(node, WHITE) == WHITE:
            yield from dfs(node)


# -- FDT303: blocking call while holding a lock ---------------------------


@conc_rule(
    "FDT303", "blocking-under-lock", "warning",
    "A blocking call (network/subprocess, or an unbounded "
    "join/wait/get/sleep) runs INSIDE a lock region — every other "
    "thread needing the lock stalls behind it, unboundedly.",
    "move the blocking call outside the region (snapshot state under "
    "the lock, block after release), or pass a timeout")
def _check_blocking_under_lock(corpus: CorpusContext) -> Iterable[Finding]:
    rule = _rule_by_id("FDT303")
    for mod in corpus.modules:
        for cls in mod.classes:
            if not cls.lock_attrs:
                continue
            for mm in cls.methods.values():
                for call in mm.calls:
                    held = cls.effective_held(mm, call.held)
                    if not held:
                        continue
                    d = call.callee
                    leaf = d.split(".")[-1]
                    blocking = None
                    if d.startswith(_BLOCKING_PREFIXES) \
                            or leaf in _BLOCKING_LEAVES_ALWAYS:
                        blocking = "a network/subprocess call"
                    elif d in ("time.sleep", "sleep") and d != "sleep":
                        blocking = "a deliberate sleep"
                    elif leaf in _BLOCKING_LEAVES_TIMEOUT \
                            and not call.has_timeout:
                        # `.wait()`/`.join()`/`.get()` with no bound;
                        # exclude the held locks' own condition methods?
                        # no — Condition.wait() under the SAME lock is
                        # legal, so skip waits on a held lock attr
                        base = _self_attr(call.node.func.value) \
                            if isinstance(call.node.func,
                                          ast.Attribute) else None
                        if base in held:
                            continue
                        blocking = f"an unbounded `.{leaf}()`"
                    if blocking is None:
                        continue
                    yield _finding(
                        rule, cls.relpath, call.node,
                        f"`{cls.name}.{mm.name}` holds "
                        f"`self.{held[0]}` across {blocking} "
                        f"(`{d}`)",
                        detail=f"{cls.name}.{mm.name}.{leaf}")


# -- FDT304: thread lifecycle ---------------------------------------------


def _daemon_kwarg(call: ast.Call) -> Optional[bool]:
    for k in call.keywords:
        if k.arg == "daemon" and isinstance(k.value, ast.Constant):
            return bool(k.value.value)
    return None


def _scope_has(node: ast.AST, pred) -> bool:
    return any(pred(n) for n in ast.walk(node))


@conc_rule(
    "FDT304", "thread-lifecycle", "warning",
    "A non-daemon Thread/Timer no code path ever joins (blocks "
    "interpreter exit, leaks on restart), or a class registers "
    "scrape-time callback gauges with no close/stop path that "
    "unregisters them (pins the dead object on shared registries).",
    "pass `daemon=True` (or `.daemon = True` before start) for "
    "fire-and-forget threads, `.join()` on the shutdown path "
    "otherwise; pair every `set_function` registration with an "
    "`unregister` in `close()`")
def _check_thread_lifecycle(corpus: CorpusContext) -> Iterable[Finding]:
    rule = _rule_by_id("FDT304")
    for mod in corpus.modules:
        # (a) non-daemon thread never joined
        for call, clsname, fn in mod.thread_sites:
            daemon = _daemon_kwarg(call)
            if daemon is True:
                continue
            # scope to search for `.daemon = True` / `.join(`: the
            # enclosing class body when inside a class, else the
            # enclosing function, else the module
            scope: ast.AST = mod.tree
            if clsname is not None:
                for c in mod.classes:
                    if c.name == clsname:
                        scope = c.node
                        break
            elif fn is not None:
                scope = fn

            def _is_daemon_set(n: ast.AST) -> bool:
                return (isinstance(n, ast.Assign)
                        and any(isinstance(t, ast.Attribute)
                                and t.attr == "daemon" for t in n.targets)
                        and isinstance(n.value, ast.Constant)
                        and bool(n.value.value))

            def _is_join(n: ast.AST) -> bool:
                return (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "join")

            if daemon is None and _scope_has(scope, _is_daemon_set):
                continue
            if _scope_has(scope, _is_join):
                continue
            where = clsname or (fn.name if fn is not None else "<module>")
            yield _finding(
                rule, mod.relpath, call,
                f"non-daemon {_dotted(call.func).split('.')[-1]} created "
                f"in `{where}` is never joined (and never marked "
                f"daemon) — it blocks interpreter exit",
                detail=f"{where}.thread")
        # (b) set_function registrations with no unregistering teardown
        for cls in mod.classes:
            if cls.defines_set_function:
                continue  # the metrics plumbing itself
            reg_node = None
            for mm in cls.methods.values():
                for callsite in mm.calls:
                    if callsite.callee.split(".")[-1] == "set_function":
                        reg_node = callsite.node
                        break
                if reg_node is not None:
                    break
            if reg_node is None:
                continue
            teardown = {"close", "stop", "shutdown", "__exit__",
                        "__del__", "unregister"}
            detaches = any(
                c.callee.split(".")[-1].startswith("unregister")
                for name, mm in cls.methods.items()
                if name in teardown
                for c in mm.calls)
            if not detaches:
                yield _finding(
                    rule, cls.relpath, reg_node,
                    f"`{cls.name}` registers callback gauges "
                    f"(`set_function`) but no close/stop path "
                    f"unregisters them — on a shared registry the dead "
                    f"object is pinned and scraped forever",
                    detail=f"{cls.name}.set_function")


# -- FDT305: unlocked module-global mutation from a thread target ---------


@conc_rule(
    "FDT305", "global-mutation-in-thread", "warning",
    "A thread-target function mutates a module global with no lock "
    "held — concurrent with every other thread touching it.",
    "guard the mutation with a module-level lock (the `_PLAN`-style "
    "install/clear pattern), or pass state through the thread's own "
    "arguments")
def _check_global_mutation(corpus: CorpusContext) -> Iterable[Finding]:
    rule = _rule_by_id("FDT305")
    for mod in corpus.modules:
        if not mod.thread_targets:
            continue
        for name in sorted(mod.thread_targets):
            for fn in mod.functions.get(name, []):
                yield from _scan_target(rule, mod, fn)


def _scan_target(rule: ConcRule, mod: _ModuleModel,
                 fn: ast.AST) -> Iterable[Finding]:
    declared_global: Set[str] = {
        n for node in ast.walk(fn) if isinstance(node, ast.Global)
        for n in node.names}
    mutable = mod.module_globals | declared_global
    if not mutable:
        return
    reported: Set[str] = set()

    def visit(n: ast.AST, held: bool) -> Iterable[Finding]:
        if isinstance(n, ast.With):
            # ANY with-region counts as synchronized — precision over
            # recall (the region is usually `with _lock:`)
            for item in n.items:
                yield from visit(item.context_expr, held)
            for child in n.body:
                yield from visit(child, True)
            return
        hits: List[Tuple[str, str]] = []
        if isinstance(n, (ast.Assign, ast.AugAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id in declared_global:
                    hits.append((t.id, "rebound"))
                elif isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in mutable:
                    hits.append((t.value.id, "subscript-mutated"))
        elif isinstance(n, ast.Call) \
                and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _MUTATORS \
                and isinstance(n.func.value, ast.Name) \
                and n.func.value.id in mutable:
            hits.append((n.func.value.id, "mutated in place"))
        for gname, what in hits:
            if not held and gname not in reported:
                reported.add(gname)
                yield _finding(
                    rule, mod.relpath, n,
                    f"thread target `{fn.name}` {what.replace('-', ' ')} "
                    f"module global `{gname}` with no lock held",
                    detail=f"{fn.name}.{gname}")
        for child in ast.iter_child_nodes(n):
            yield from visit(child, held)

    for child in ast.iter_child_nodes(fn):
        yield from visit(child, False)


# -- entry point -----------------------------------------------------------


def run_concurrency_checks(
        paths: Optional[Sequence[str]] = None,
        root: Optional[str] = None,
        rules: Optional[Sequence[ConcRule]] = None) -> List[Finding]:
    """Parse ``paths`` (default: the repo's standard scan roots) and run
    the FDT3xx registry over the whole corpus.  Unparsable files are
    skipped here — layer 1's FDT000 already gates them."""
    from .engine import _relpath, default_roots, iter_py_files

    modules: List[_ModuleModel] = []
    for path in iter_py_files(list(paths) if paths else default_roots()):
        try:
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (OSError, SyntaxError, ValueError):
            continue
        modules.append(_build_module(path, _relpath(path, root), tree))
    corpus = CorpusContext(modules)
    out: List[Finding] = []
    for rule in (rules or CONC_RULES):
        out.extend(rule.check(corpus))
    out.sort(key=lambda f: (f.file, f.line, f.rule))
    return out
