"""fdtpu-lint suite tests (ISSUE 5).

Three blocks:

* **AST rules** — every rule in ``analysis.rules_ast`` against its
  fixture pair in ``tests/fixtures_analysis/`` (positive fires exactly
  its rule; negative fires nothing), plus findings/baseline machinery.
* **jaxpr layer** — deliberately mis-sharded / mis-donated /
  nondeterministic / transfer-dirty toy steps each producing their
  distinct finding (FDT201–FDT205), and the full registered-variant
  sweep (dp, zero1, fsdp, tp, pp_1f1b, context, serve) coming back
  clean on the 8-virtual-device CPU mesh.
* **CLI + strict_checks** — ``bin/lint.py`` exit codes / baseline
  workflow end-to-end, and the ``prepare_training(strict_checks=True)``
  first-step guard.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from fluxdistributed_tpu import analysis
from fluxdistributed_tpu.analysis import engine as engine_mod
from fluxdistributed_tpu.analysis import jaxpr_checks, rules_ast
from fluxdistributed_tpu.analysis.findings import Finding
from fluxdistributed_tpu.analysis.variants import StepVariant

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures_analysis")
REPO = engine_mod.repo_root()
LINT = os.path.join(REPO, "bin", "lint.py")
RULE_IDS = [r.id for r in rules_ast.AST_RULES]


def _scan(name):
    return engine_mod.scan_file(os.path.join(FIXTURES, name))


def _lint(*args, timeout=180):
    return subprocess.run(
        [sys.executable, LINT, *args], cwd=REPO,
        capture_output=True, text=True, timeout=timeout)


# ---------------------------------------------------------------- AST rules

def test_rule_registry_complete():
    # one fixture pair per registered rule — adding a rule without
    # fixtures fails here, which is the "how to add a rule" contract
    assert RULE_IDS == [f"FDT10{i}" for i in range(1, 8)]
    for rid in RULE_IDS:
        for pol in ("pos", "neg"):
            assert os.path.exists(
                os.path.join(FIXTURES, f"{rid.lower()}_{pol}.py"))


@pytest.mark.parametrize("rid", RULE_IDS)
def test_ast_rule_positive(rid):
    findings = _scan(f"{rid.lower()}_pos.py")
    assert findings, f"{rid} positive fixture produced no findings"
    assert {f.rule for f in findings} == {rid}
    for f in findings:
        assert f.line > 0 and f.hint and f.detail
        assert f.severity in analysis.SEVERITIES


@pytest.mark.parametrize("rid", RULE_IDS)
def test_ast_rule_negative(rid):
    assert _scan(f"{rid.lower()}_neg.py") == []


def test_parse_error_is_finding(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    fs = engine_mod.scan_file(str(bad), root=str(tmp_path))
    assert [f.rule for f in fs] == ["FDT000"]
    assert fs[0].severity == "error"


def test_unknown_axis_is_error_known_literal_is_warning():
    fs = _scan("fdt105_pos.py")
    by_detail = {f.detail: f for f in fs}
    unknown = next(f for f in fs if "nonexistent_axis" in f.detail)
    assert unknown.severity == "error"
    known = next(f for f in fs if f.detail.endswith("P:data"))
    assert known.severity == "warning"
    assert len(by_detail) == len(fs)  # details are distinct baseline keys


def test_repo_scan_clean_and_baseline_small():
    # satellite 1: every in-repo warning+ finding fixed; the committed
    # baseline stays within the acceptance budget (<= 5 entries)
    findings = analysis.scan_repo()
    base = analysis.load_baseline(analysis.default_baseline_path())
    assert len(base) <= 5
    new, _ = analysis.diff_findings(findings, base)
    assert new == [], "\n".join(analysis.format_finding(f) for f in new)


def test_declared_mesh_axes_match_mesh_module():
    from fluxdistributed_tpu import mesh as mesh_lib

    assert rules_ast.declared_mesh_axes() == {
        mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS, mesh_lib.MODEL_AXIS,
        mesh_lib.SEQ_AXIS, mesh_lib.PIPE_AXIS, mesh_lib.EXPERT_AXIS}


# ------------------------------------------------------- findings/baseline

def _toy_finding(detail="f", line=3):
    return Finding(rule="FDT101", severity="warning", file="a.py",
                   line=line, message="m", hint="h", detail=detail)


def test_baseline_round_trip(tmp_path):
    path = str(tmp_path / "base.json")
    fs = [_toy_finding("a"), _toy_finding("b")]
    analysis.save_baseline(path, fs)
    new, stale = analysis.diff_findings(fs, analysis.load_baseline(path))
    assert new == [] and stale == []


def test_baseline_is_line_number_free(tmp_path):
    path = str(tmp_path / "base.json")
    analysis.save_baseline(path, [_toy_finding(line=3)])
    moved = [_toy_finding(line=99)]  # unrelated edit shifted the file
    new, stale = analysis.diff_findings(moved, analysis.load_baseline(path))
    assert new == [] and stale == []


def test_baseline_new_and_stale():
    base = [{"rule": "FDT101", "file": "a.py", "detail": "gone"}]
    new, stale = analysis.diff_findings([_toy_finding("fresh")], base)
    assert [f.detail for f in new] == ["fresh"]
    assert [e["detail"] for e in stale] == ["gone"]


def test_format_finding_names_rule_and_location():
    s = analysis.format_finding(_toy_finding())
    assert "a.py:3:" in s and "[FDT101]" in s and "hint:" in s


def test_lint_verdict_shape():
    v = analysis.lint_verdict()
    assert set(v) >= {"findings", "by_severity", "by_rule", "new", "baseline"}
    assert v["new"] == 0  # the repo itself must stay clean


# ------------------------------------------------------------- jaxpr layer

@pytest.fixture(scope="module")
def mesh8():
    from fluxdistributed_tpu import mesh as mesh_lib

    return mesh_lib.data_mesh(8)


def test_spec_invalid_axis(mesh8):
    from jax.sharding import PartitionSpec as P

    fs = jaxpr_checks.check_spec_tree(
        {"w": (8, 4)}, {"w": P("nonexistent")}, mesh8, where="toy")
    assert [f.rule for f in fs] == ["FDT201"]
    assert "nonexistent" in fs[0].message


def test_spec_non_divisible(mesh8):
    from jax.sharding import PartitionSpec as P
    from fluxdistributed_tpu.mesh import DATA_AXIS

    fs = jaxpr_checks.check_spec_tree(
        {"w": (6, 4)}, {"w": P(DATA_AXIS)}, mesh8, where="toy")
    assert [f.rule for f in fs] == ["FDT202"]
    assert "divisible" in fs[0].message


def test_spec_rank_overflow_and_clean(mesh8):
    from jax.sharding import PartitionSpec as P
    from fluxdistributed_tpu.mesh import DATA_AXIS

    fs = jaxpr_checks.check_spec_tree(
        {"w": (8,)}, {"w": P(None, DATA_AXIS)}, mesh8, where="toy")
    assert [f.rule for f in fs] == ["FDT201"]
    assert jaxpr_checks.check_spec_tree(
        {"w": (16, 4), "b": (4,)},
        {"w": P(DATA_AXIS, None), "b": None}, mesh8, where="toy") == []


def test_fdt108_committed_tables_clean():
    """The committed rule tables (parallel/rules.py RULE_TABLES) carry
    no dead rules and no silently-replicating large leaves on their
    registered probe models — the baseline-stays-EMPTY contract
    extended to declarative sharding data."""
    assert jaxpr_checks.check_rule_tables() == []


def test_fdt108_dead_rule_and_large_unmatched():
    import numpy as np

    from fluxdistributed_tpu.parallel.rules import RuleTable

    def probe():
        # one large leaf (embedding-sized) + one small one
        return ({"embed": {"table": np.zeros((1024, 8), np.float32)},
                 "norm": {"scale": np.zeros((8,), np.float32)}},
                "toy-probe")

    def bad_table():
        from jax.sharding import PartitionSpec as P

        from fluxdistributed_tpu.mesh import DATA_AXIS

        # typo'd path: matches nothing; nothing covers the big leaf
        return [(r"embedd/tabel$", P(DATA_AXIS, None))]

    tables = {"toy": RuleTable("toy", bad_table, probes=(probe,))}
    fs = jaxpr_checks.check_rule_tables(tables)
    assert sorted(f.detail for f in fs) == [
        "toy:dead:embedd/tabel$", "toy:unmatched:embed/table"]
    assert all(f.rule == "FDT108" for f in fs)
    assert "dead rule" in fs[0].message or "dead rule" in fs[1].message
    # the small leaf replicates by design — never a finding
    assert not any("norm/scale" in f.detail for f in fs)
    # a table that opts out of the unmatched check (dp/fsdp semantics)
    # only reports the dead rule
    tables = {"toy": RuleTable("toy", bad_table, probes=(probe,),
                               check_unmatched=False)}
    assert [f.detail for f in jaxpr_checks.check_rule_tables(tables)] \
        == ["toy:dead:embedd/tabel$"]


def test_fdt108_duplicate_pattern_flagged():
    """A duplicated pattern is unreachable under first-match-wins (and
    would collapse in the aliveness dict) — flagged outright, not
    silently reported alive."""
    import numpy as np

    from fluxdistributed_tpu.parallel.rules import RuleTable

    def probe():
        return ({"qkv": {"kernel": np.zeros((8, 8), np.float32)}},
                "toy-probe")

    def dup_table():
        from jax.sharding import PartitionSpec as P

        from fluxdistributed_tpu.mesh import DATA_AXIS, MODEL_AXIS

        return [(r"qkv/kernel$", P(DATA_AXIS, None)),
                (r"qkv/kernel$", P(None, MODEL_AXIS))]  # unreachable

    tables = {"toy": RuleTable("toy", dup_table, probes=(probe,),
                               check_unmatched=False)}
    fs = jaxpr_checks.check_rule_tables(tables)
    assert [f.detail for f in fs] == ["toy:duplicate:qkv/kernel$"]
    assert "unreachable" in fs[0].message


def test_donation_dropped(mesh8):
    import jax
    import jax.numpy as jnp

    def step(state, batch):
        return {"w": state["w"] + batch.sum()}  # "m" never returned

    st = {"w": jnp.zeros((4, 4)), "m": jnp.zeros((8,))}
    v = StepVariant(
        name="toy-donate", fn=jax.jit(step, donate_argnums=(0,)),
        args=(st, jnp.ones(3)), donate_argnums=(0,), mesh=mesh8,
        source="toy.py")
    fs = jaxpr_checks.check_donation(v)
    assert [f.rule for f in fs] == ["FDT203"]
    assert "no matching output" in fs[0].message


def test_donation_consumable_is_clean(mesh8):
    import jax
    import jax.numpy as jnp

    def step(state, batch):
        return {"w": state["w"] + batch.sum(), "m": state["m"] * 0.9}

    st = {"w": jnp.zeros((4, 4)), "m": jnp.zeros((8,))}
    v = StepVariant(
        name="toy-donate-ok", fn=jax.jit(step, donate_argnums=(0,)),
        args=(st, jnp.ones(3)), donate_argnums=(0,), mesh=mesh8,
        source="toy.py")
    assert jaxpr_checks.check_donation(v) == []


class _FakeLowered:
    def __init__(self, text):
        self._text = text

    def as_text(self):
        return self._text


class _DriftingFn:
    """A program whose lowering differs every trace — the ambient-state
    capture FDT204 exists to catch (jit caches lowerings, so the real
    repro needs a stub)."""

    def __init__(self, drift=True):
        self.drift = drift
        self.n = 0

    def lower(self, *args):
        self.n += 1
        return _FakeLowered(f"program-{self.n if self.drift else 0}")


def test_retrace_drift_detected(mesh8):
    import jax.numpy as jnp

    v = StepVariant(name="toy-drift", fn=_DriftingFn(), args=(jnp.ones(4),),
                    donate_argnums=(), mesh=mesh8, source="toy.py")
    fs = jaxpr_checks.check_retrace(v)
    assert [f.rule for f in fs] == ["FDT204"]
    assert "AOT" in fs[0].message


def test_retrace_stable_is_clean(mesh8):
    import jax.numpy as jnp

    v = StepVariant(name="toy-stable", fn=_DriftingFn(drift=False),
                    args=(jnp.ones(4),), donate_argnums=(), mesh=mesh8,
                    source="toy.py")
    assert jaxpr_checks.check_retrace(v) == []


def test_transfer_guard_flags_uncommitted_input(mesh8):
    import jax

    # numpy args re-transfer host->device on EVERY call — the steady
    # state the guarded second call runs under
    v = StepVariant(
        name="toy-transfer", fn=jax.jit(lambda x: x * 2.0),
        args=(np.ones(8, np.float32),), donate_argnums=(), mesh=mesh8,
        source="toy.py", execute=True, carry=lambda a, o: a)
    fs = jaxpr_checks.check_transfers(v)
    assert [f.rule for f in fs] == ["FDT205"]


def test_transfer_guard_clean_when_committed(mesh8):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    x = jax.device_put(np.ones(8, np.float32),
                       NamedSharding(mesh8, PartitionSpec()))
    v = StepVariant(
        name="toy-committed", fn=jax.jit(lambda x: x * 2.0), args=(x,),
        donate_argnums=(), mesh=mesh8, source="toy.py", execute=True,
        carry=lambda a, o: a)
    assert jaxpr_checks.check_transfers(v) == []


def test_broken_builder_is_finding(monkeypatch):
    from fluxdistributed_tpu.analysis import variants as variants_mod

    def boom():
        raise RuntimeError("factory exploded")

    monkeypatch.setitem(variants_mod.VARIANT_BUILDERS, "broken", boom)
    fs = jaxpr_checks.run_jaxpr_checks(names=["broken"])
    assert [f.rule for f in fs] == ["FDT200"]
    assert "factory exploded" in fs[0].message


def test_unknown_variant_raises():
    with pytest.raises(ValueError, match="unknown variant"):
        from fluxdistributed_tpu.analysis.variants import build_variants

        build_variants(["typo"])


def test_all_registered_variants_clean():
    # the acceptance sweep: dp, zero1, fsdp, tp, pp_1f1b, context (and
    # the serve program pool) all trace/validate clean on the 8-device
    # CPU mesh — sharding specs, donation vectors, retrace digests, and
    # (for the execute-marked variants) transfer-guarded steady state
    fs = jaxpr_checks.run_jaxpr_checks()
    assert fs == [], "\n".join(analysis.format_finding(f) for f in fs)


# ---------------------------------------------------------------- lint CLI

def test_cli_fixtures_fail_check():
    p = _lint("tests/fixtures_analysis", "--check")
    assert p.returncode == 1
    # acceptance: names rule id + file:line for the seeded violations
    for rid in RULE_IDS:
        assert f"[{rid}]" in p.stdout
        assert f"{rid.lower()}_pos.py:" in p.stdout


def test_cli_repo_clean():
    # AST layer over the real repo: exits 0 against the committed
    # baseline (the jaxpr half is covered in-process above — no need to
    # re-trace every variant in a subprocess)
    p = _lint("--check", "--no-jaxpr")
    assert p.returncode == 0, p.stdout + p.stderr


def test_cli_update_baseline_workflow(tmp_path):
    base = str(tmp_path / "baseline.json")
    p = _lint("tests/fixtures_analysis", "--baseline", base,
              "--update-baseline")
    assert p.returncode == 0
    entries = json.load(open(base))
    assert entries and all({"rule", "file", "detail"} <= set(e)
                           for e in entries)
    # everything baselined -> --check passes; fixing a finding leaves a
    # reported (non-fatal) stale entry
    p = _lint("tests/fixtures_analysis", "--baseline", base, "--check")
    assert p.returncode == 0
    p = _lint("tests/fixtures_analysis/fdt101_pos.py", "--baseline", base,
              "--check")
    assert p.returncode == 0
    assert "stale baseline entry" in p.stdout


def test_cli_partial_update_keeps_out_of_scope_entries(tmp_path):
    # a scoped --update-baseline must not erase allowlist entries the
    # scan could not have re-observed: AST entries for unscanned files
    # and jaxpr-layer entries when the jaxpr layer did not run
    base = tmp_path / "baseline.json"
    kept_ast = {"rule": "FDT105", "file": "fluxdistributed_tpu/other.py",
                "detail": "f:P:bogus"}
    kept_jaxpr = {"rule": "FDT203", "file": "toy.py", "detail": "v:arg0"}
    base.write_text(json.dumps([kept_ast, kept_jaxpr]))
    p = _lint("tests/fixtures_analysis/fdt101_pos.py", "--baseline",
              str(base), "--update-baseline")
    assert p.returncode == 0
    entries = json.loads(base.read_text())
    keys = {(e["rule"], e["file"], e["detail"]) for e in entries}
    assert ("FDT105", kept_ast["file"], kept_ast["detail"]) in keys
    assert ("FDT203", "toy.py", "v:arg0") in keys
    assert any(e["rule"] == "FDT101" for e in entries)


def test_axis_rule_stands_down_when_axes_unknown(tmp_path):
    # an unparseable mesh.py means axes are UNKNOWN, not that every
    # literal is undeclared — FDT105 must not bury the real FDT000
    # under repo-wide false errors
    import ast as ast_mod

    from fluxdistributed_tpu.analysis.rules_ast import (
        ModuleContext, declared_mesh_axes, run_ast_rules)

    bad = tmp_path / "mesh.py"
    bad.write_text("DATA_AXIS = (\n")
    assert declared_mesh_axes(str(bad)) == set()
    src = open(os.path.join(FIXTURES, "fdt105_pos.py")).read()
    ctx = ModuleContext("fdt105_pos.py", "fdt105_pos.py", src,
                        ast_mod.parse(src), axes=set())
    assert [f for f in run_ast_rules(ctx) if f.rule == "FDT105"] == []


def test_cli_json_output():
    p = _lint("tests/fixtures_analysis/fdt101_pos.py", "--json")
    assert p.returncode == 0
    out = json.loads(p.stdout)
    assert {f["rule"] for f in out["findings"]} == {"FDT101"}
    assert out["summary"]["by_rule"]["FDT101"] == len(out["findings"])


def test_cli_missing_baseline_is_usage_error():
    p = _lint("--check", "--no-jaxpr", "--baseline", "no/such/file.json")
    assert p.returncode == 2


# ------------------------------------------------------------ strict_checks

def _toy_task(strict=True):
    from fluxdistributed_tpu import mesh as mesh_lib, optim
    from fluxdistributed_tpu.data.synthetic import SyntheticDataset
    from fluxdistributed_tpu.models.simple import SimpleCNN
    from fluxdistributed_tpu.train.trainer import _dummy_batch, prepare_training

    model = SimpleCNN(num_classes=4, features=8)
    ds = SyntheticDataset(nsamples=32, nclasses=4, shape=(8, 8, 3))
    mesh = mesh_lib.data_mesh(8)
    task = prepare_training(model, ds, optim.adam(1e-3), mesh=mesh,
                            batch_size=16, cycles=1, strict_checks=strict)
    return task, _dummy_batch(ds, None, 16, mesh, 1, seed=0)


def test_strict_checks_clean_run():
    import jax

    task, batch = _toy_task()
    state, m = task.step_fn(task.state, batch)  # call 1: NaN-debug
    state, m = task.step_fn(state, batch)  # call 2: transfer guard
    state, m = task.step_fn(state, batch)  # disarmed fast path
    assert np.isfinite(float(m["loss"]))
    assert not jax.config.jax_debug_nans  # flag restored


def test_strict_checks_names_nan_phase():
    import jax.numpy as jnp

    task, batch = _toy_task()
    bad = dict(batch)
    bad["image"] = batch["image"] * jnp.float32(np.nan)
    with pytest.raises(FloatingPointError, match="first train step"):
        task.step_fn(task.state, bad)


def test_strict_checks_names_transfer_phase():
    task, batch = _toy_task()
    state, _ = task.step_fn(task.state, batch)
    # an uncommitted numpy batch on the guarded steady-state call is
    # exactly the recurring per-step transfer the check exists to catch
    host_batch = {k: np.asarray(v) for k, v in batch.items()}
    with pytest.raises(RuntimeError, match="steady-state train step"):
        task.step_fn(state, host_batch)
