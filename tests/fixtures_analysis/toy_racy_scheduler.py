"""The intentionally-racy toy for the schedule harness
(tests/test_schedules.py loads it via importlib — like every fixture
here it is outside the default lint scan).

`submit` locks the read and locks the write but DROPS the lock between
them, so a concurrent submit in the window is silently overwritten — a
lost update.  Deliberately shaped so the static layer stays quiet
(every access holds the lock; FDT301's coverage model cannot see a
split atomicity assumption): this is precisely the residual bug class
the deterministic-schedule harness exists for.  The window is a few
bytecodes wide — under CPython's 5 ms GIL switch interval it
essentially never loses on its own, which is what makes the
catches-with/misses-without pair in test_schedules.py a real guard
against the harness becoming a no-op.
"""
import threading

from fluxdistributed_tpu.analysis import schedules


class RacyToyScheduler:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def submit(self, n=1):
        with self._lock:
            current = self.total
        # BUG: the lock is dropped here — another submit() landing in
        # this window is overwritten by the stale `current + n` below
        with self._lock:
            self.total = current + n


class FixedToyScheduler:
    """The fix the harness pins: one lock region spans read and write."""

    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def submit(self, n=1):
        with self._lock:
            self.total = self.total + n


def hammer(sched, workers=2, per_worker=1):
    """`workers` threads, barrier-released together, each submitting
    `per_worker` times — returns the final total (correct value:
    workers * per_worker)."""
    barrier = threading.Barrier(workers)

    def run():
        barrier.wait()
        for _ in range(per_worker):
            sched.submit(1)

    threads = [threading.Thread(target=run, name=f"hammer-{i}")
               for i in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return sched.total


def lost_update_under(plan, cls=RacyToyScheduler):
    """Instrument a fresh scheduler, hammer it under `plan`, report
    whether an update was lost.  The forced preemption at the FIRST
    `.release` crossing lands exactly in the read→write window: the
    stalled thread resumes with a stale `current` and overwrites the
    other thread's completed submit."""
    sched = schedules.instrument(cls())
    total = schedules.run_under_schedule(plan, lambda: hammer(sched))
    return total != 2
