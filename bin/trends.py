#!/usr/bin/env python
"""Cross-run trend tables, regression gating and postmortems.

The consumer side of the ``obs.runs`` ledger (``runs.jsonl``: one
record per training run / bench round / supervisor episode, keyed by
topology fingerprint)::

    # trend tables + newest records (the default view)
    python bin/trends.py --ledger benchmarks/hw/runs.jsonl

    # CI gate: exit 2 when the newest value of any gated metric moved
    # past tolerance in the BAD direction vs its per-topology rolling
    # baseline (good-direction moves are notes — re-record, don't gate)
    python bin/trends.py --check

    # backfill the ledger from archived round files (idempotent by
    # source basename — phase/retryable/probe_attempts preserved)
    python bin/trends.py --ingest 'benchmarks/hw/BENCH_r*.json' \
        'benchmarks/hw/MULTICHIP_r*.json'

    # one human-readable account of how a round died: newest flight
    # dump + supervisor episode ledger + bench phase status merged
    python bin/trends.py --postmortem --flight run/flight.jsonl \
        --supervisor-ledger run/ledger.json

Exit codes: 0 clean, 2 regression detected (``--check``), 1 usage /
missing ledger.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # direct `python bin/trends.py` launches
    sys.path.insert(0, REPO)

from fluxdistributed_tpu.obs import runs as runs_lib  # noqa: E402

DEFAULT_LEDGER = os.path.join(REPO, "benchmarks", "hw", "runs.jsonl")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--ledger", default=DEFAULT_LEDGER, metavar="PATH",
                   help="the runs.jsonl ledger to read/append "
                        f"(default: {DEFAULT_LEDGER})")
    p.add_argument("--check", action="store_true",
                   help="regression gate: exit 2 when any gated metric "
                        "regressed past tolerance vs its per-topology "
                        "rolling baseline")
    p.add_argument("--window", type=int, default=5, metavar="N",
                   help="rolling-baseline window: the median of up to N "
                        "predecessors (default 5)")
    p.add_argument("--ingest", nargs="+", default=None, metavar="GLOB",
                   help="backfill: ingest archived BENCH_r*.json / "
                        "MULTICHIP_r*.json round files into the ledger "
                        "(idempotent by source basename)")
    p.add_argument("--postmortem", action="store_true",
                   help="merge the evidence below into one "
                        "human-readable timeline of how a run died")
    p.add_argument("--flight", default=None, metavar="PATH",
                   help="flight dump for --postmortem")
    p.add_argument("--supervisor-ledger", default=None, metavar="PATH",
                   help="supervisor episode ledger for --postmortem")
    p.add_argument("--bench-status", default=None, metavar="PATH",
                   help="bench.py --resumable status JSON for "
                        "--postmortem")
    p.add_argument("--limit", type=int, default=20, metavar="N",
                   help="newest records to render (default 20)")
    args = p.parse_args(argv)

    if args.ingest:
        paths = []
        for pat in args.ingest:
            hits = glob.glob(pat)
            if not hits:
                print(f"ingest: no files match {pat!r}", file=sys.stderr)
            paths.extend(hits)
        added, skipped = runs_lib.ingest_paths(args.ledger, paths)
        print(f"ingested {added} record(s) into {args.ledger} "
              f"({skipped} skipped: already present or unparseable)")
        return 0

    if args.postmortem:
        print(runs_lib.postmortem_timeline(
            flight_path=args.flight,
            supervisor_ledger=args.supervisor_ledger,
            bench_status=args.bench_status,
            runs_path=args.ledger if os.path.exists(args.ledger)
            else None,
        ))
        return 0

    runs = runs_lib.load_runs(args.ledger)
    if not runs:
        print(f"no ledger at {args.ledger} (or it is empty) — run "
              "--ingest, or point --ledger at one", file=sys.stderr)
        return 1

    print(f"== {args.ledger}: {len(runs)} record(s) ==")
    print(runs_lib.render_runs(runs, limit=args.limit))
    print()
    print(runs_lib.trend_table(runs, window=args.window))
    verdicts = runs_lib.check_regressions(runs, window=args.window)
    for note in verdicts["notes"]:
        print(f"note: {note}")
    for fail in verdicts["failures"]:
        print(f"REGRESSION: {fail}")
    if args.check and verdicts["failures"]:
        return 2
    if args.check:
        print("check: no regressions "
              f"({len(verdicts['notes'])} note(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
