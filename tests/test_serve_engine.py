"""Continuous-batching engine + scheduler (fluxdistributed_tpu.serve).

The golden test is TOKEN-FOR-TOKEN parity: every request served by the
slot engine under interleaved admissions must reproduce exactly what a
sequential ``models.generate`` call produces for that prompt — across
plain, window+sinks, GQA, and learned-position configs.  The rest are
the scheduler's contractual edge cases: slot exhaustion queues, EOS
mid-batch frees a slot that is re-admitted within the same step, an
over-long prompt raises an actionable ValueError, the bounded queue
sheds load, and steady-state decode holds at ONE compiled step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu.models import generate, lm_tiny
from fluxdistributed_tpu.serve import LMEngine, QueueFull, Request, Scheduler

CONFIGS = {
    "plain": {},
    "window_sinks": {"window": 8, "sinks": 2},
    "gqa": {"num_kv_heads": 2},
    "window_gqa": {"window": 6, "sinks": 1, "num_kv_heads": 2},
}


def _make(config, vocab=32, **model_kw):
    model = lm_tiny(vocab=vocab, depth=2, dim=64, mlp_dim=128,
                    dtype=jnp.float32, **CONFIGS[config], **model_kw)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 2), np.int32), train=False
    )["params"]
    return model, params


def _ref(model, params, prompt, new):
    dm = model.clone(decode=True)
    out = generate(dm, params, np.asarray([prompt], np.int32),
                   total_len=len(prompt) + new)
    return list(np.asarray(out)[0])


# tier-1 runs the plain axis; the window/GQA configs ride the slow job
# (their engine-level parity is also covered there by test_serve_paged
# and test_pallas_decode matrices, and windowed/GQA DECODE math stays
# fast via the kernel parity tests + test_transformer_lm) — the tier-1
# loop must hold the 870s verify window (ROADMAP)
@pytest.mark.parametrize("config", [
    "plain",
    pytest.param("gqa", marks=pytest.mark.slow),
    pytest.param("window_sinks", marks=pytest.mark.slow),
    pytest.param("window_gqa", marks=pytest.mark.slow),
])
def test_parity_interleaved_admissions(config):
    """Engine output == sequential generate() for every request, with
    admissions arriving mid-flight and prompts spanning both buckets."""
    model, params = _make(config)
    engine = LMEngine(model, params, max_slots=3, max_len=32, buckets=(4, 8))
    sched = Scheduler(engine, max_queue=16)
    rng = np.random.default_rng(7)
    prompts = [list(rng.integers(0, 32, n)) for n in (3, 2, 5, 1, 8, 7)]
    reqs = [Request(prompt=p, max_new_tokens=9) for p in prompts]
    # interleave: 2 up front, 2 after a couple of steps, 2 more later
    sched.submit(reqs[0]); sched.submit(reqs[1])
    sched.step(); sched.step()
    sched.submit(reqs[2]); sched.submit(reqs[3])
    sched.step()
    sched.submit(reqs[4]); sched.submit(reqs[5])
    sched.run_until_idle()
    for r, p in zip(reqs, prompts):
        assert r.tokens == _ref(model, params, p, 9), (config, p)


def test_windowed_ring_exact_no_slack():
    """The dynamic valid-length prefill operand drops the ring_slack
    over-allocation: a windowed engine's per-slot KV rows are EXACTLY
    sinks + window — with a bucket ladder whose pad runs dwarf the
    window (the configuration that, pre-gate, needed slack >= the
    largest inter-bucket gap to avoid pad eviction) — and golden token
    parity still holds, at ONE prefill compile per bucket.  The
    reclaimed bytes surface through reserved_kv_bytes: reserved ==
    predicted == rows x (sinks + window) x per-row bytes."""
    model, params = _make("window_sinks")  # window=8, sinks=2
    # buckets (4, 32): a 5-token prompt pads by 27 — over 3x the window
    engine = LMEngine(model, params, max_slots=2, max_len=32,
                      buckets=(4, 32))
    assert engine.kv_rows_per_slot == 8 + 2
    kv = engine.kv_cache_bytes()
    assert kv["reserved"] == kv["predicted"]
    sched = Scheduler(engine, max_queue=8)
    rng = np.random.default_rng(3)
    prompts = [list(rng.integers(0, 32, n)) for n in (5, 3, 12)]
    reqs = [Request(prompt=p, max_new_tokens=8) for p in prompts]
    sched.submit(reqs[0]); sched.submit(reqs[1])
    sched.step()
    sched.submit(reqs[2])
    sched.run_until_idle()
    for r, p in zip(reqs, prompts):
        assert r.tokens == _ref(model, params, p, 8), p
    stats = engine.compile_stats()
    assert stats["decode_compiles"] in (-1, 1)
    assert stats["prefill_compiles"] in (-1, 2)  # one per bucket


def test_engine_pins_user_ring_slack_to_zero():
    """A user model carrying ring_slack>0 must not desynchronize the
    engine's exact sinks+window accounting: the clones pin slack to 0,
    so reserved==predicted holds and parity is unchanged."""
    model, params = _make("window_sinks", ring_slack=4)
    engine = LMEngine(model, params, max_slots=2, max_len=32,
                      buckets=(8, 32))
    assert engine.kv_rows_per_slot == 8 + 2
    assert engine.decode_model.ring_slack == 0
    kv = engine.kv_cache_bytes()
    assert kv["reserved"] == kv["predicted"]
    sched = Scheduler(engine, max_queue=4)
    p = list(np.random.default_rng(9).integers(0, 32, 6))
    r = Request(prompt=p, max_new_tokens=6)
    sched.submit(r)
    sched.run_until_idle()
    # the reference clone carries the user's slack (a larger retention
    # ring never changes band semantics) — parity must hold across it
    assert r.tokens == _ref(model, params, p, 6)


def test_windowed_chunked_prefill_exact_ring():
    """Dense CHUNKED prefill (prefill_chunk smaller than the window's
    pad runs) through the exactly-sized ring: each chunk's valid length
    rides the same dynamic operand, so a padded final chunk cannot
    evict in-band keys."""
    model, params = _make("window_sinks")
    engine = LMEngine(model, params, max_slots=2, max_len=32,
                      buckets=(32,), prefill_chunk=8)
    assert engine.kv_rows_per_slot == 8 + 2
    sched = Scheduler(engine, max_queue=8)
    rng = np.random.default_rng(5)
    prompts = [list(rng.integers(0, 32, n)) for n in (13, 9)]
    reqs = [Request(prompt=p, max_new_tokens=6) for p in prompts]
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()
    for r, p in zip(reqs, prompts):
        assert r.tokens == _ref(model, params, p, 6), p


def test_parity_learned_positions():
    """use_rope=False (the GPT-2 interop layout) decodes through per-slot
    pos_index cursors with the same parity guarantee."""
    model, params = _make("plain", use_rope=False, max_len=24)
    engine = LMEngine(model, params, max_slots=2, max_len=24, buckets=(4,))
    sched = Scheduler(engine)
    prompts = [[5, 3, 7], [1, 2], [4, 4, 4, 1]]
    reqs = [Request(prompt=p, max_new_tokens=6) for p in prompts]
    sched.generate_all(reqs)
    for r, p in zip(reqs, prompts):
        assert r.tokens == _ref(model, params, p, 6)


def test_slot_exhaustion_queues():
    """More requests than slots: the surplus WAITS (FIFO) instead of
    erroring, active slots never exceed the pool, and everyone still
    gets sequential-parity output."""
    model, params = _make("plain")
    engine = LMEngine(model, params, max_slots=2, max_len=32, buckets=(4,))
    sched = Scheduler(engine, max_queue=8)
    prompts = [[1], [2], [3], [4], [5]]
    reqs = [Request(prompt=p, max_new_tokens=5) for p in prompts]
    for r in reqs:
        sched.submit(r)
    assert sched.queue_depth == 5
    sched.step()
    assert sched.active_slots == 2 and sched.queue_depth == 3
    seen_active = []
    while not sched.idle:
        seen_active.append(sched.active_slots)
        sched.step()
    assert max(seen_active) <= 2
    for r, p in zip(reqs, prompts):
        assert r.state == "done"
        assert r.tokens == _ref(model, params, p, 5)
    # FIFO: the first submission is never finished after the last one
    assert reqs[0].finished_at <= reqs[-1].finished_at


def test_eos_mid_batch_frees_slot_readmitted_same_step():
    """An EOS finishing one request mid-batch frees its slot, and a
    queued request is admitted (prefill + first token) within the SAME
    scheduler step — continuous batching, not gang scheduling."""
    model, params = _make("plain")
    # learn what the model will actually emit so we can plant an EOS on
    # the SECOND generated token (mid-decode, not at admission); search
    # for a prompt whose first two generated tokens differ, so the EOS
    # cannot fire already at admission
    for cand in ([5, 3], [9, 1], [2, 8], [7, 7], [11, 4], [3, 14]):
        probe = _ref(model, params, cand, 4)
        if probe[2] != probe[3]:
            p1, eos = cand, probe[3]
            break
    else:
        pytest.fail("no probe prompt with distinct first two generations")
    engine = LMEngine(model, params, max_slots=1, max_len=16, buckets=(4,))
    sched = Scheduler(engine, max_queue=4)
    r1 = Request(prompt=p1, max_new_tokens=8, eos_id=eos)
    r2 = Request(prompt=[1, 2], max_new_tokens=3)
    sched.submit(r1)
    sched.step()  # admits r1, emits first token (not EOS)
    assert r1.state == "active" and sched.active_slots == 1
    sched.submit(r2)
    assert r2.state == "queued" and sched.queue_depth == 1  # slot-starved
    sched.step()  # decode emits r1's EOS -> slot freed -> r2 admitted
    assert r1.state == "done" and r1.generated[-1] == eos
    assert r2.state == "active" and len(r2.generated) == 1  # same step!
    assert sched.queue_depth == 0
    sched.run_until_idle()
    # r1 stopped AT the EOS; its tokens are the sequential prefix
    assert r1.tokens == probe[:4]
    assert r2.tokens == _ref(model, params, [1, 2], 3)


def test_prompt_longer_than_largest_bucket_raises():
    model, params = _make("plain")
    engine = LMEngine(model, params, max_slots=1, max_len=32, buckets=(4, 8))
    # the bucket ladder always tops out AT max_len, so anything the slot
    # cache can hold is servable...
    assert engine.buckets == (4, 8, 32)
    # ...and past it, the error is actionable (names limit and fix)
    with pytest.raises(ValueError, match="largest prefill bucket"):
        sched = Scheduler(engine)
        sched.submit(Request(prompt=list(range(33)), max_new_tokens=2))
    # budget overflow is a different, equally actionable message
    with pytest.raises(ValueError, match="max_len"):
        sched.submit(Request(prompt=[1, 2], max_new_tokens=31))
    # both rejected BEFORE touching any slot
    assert sched.idle and sched.metrics()["requests_submitted"] == 0


def test_queue_full_backpressure():
    model, params = _make("plain")
    engine = LMEngine(model, params, max_slots=1, max_len=16, buckets=(4,))
    sched = Scheduler(engine, max_queue=2)
    for p in ([1], [2]):
        sched.submit(Request(prompt=p, max_new_tokens=4))
    with pytest.raises(QueueFull):
        sched.submit(Request(prompt=[3], max_new_tokens=4))
    assert sched.metrics()["requests_rejected"] == 1
    sched.run_until_idle()  # the accepted ones still drain


def test_no_recompile_after_warmup():
    """Steady-state serving reuses ONE compiled decode step and one
    prefill per bucket — admissions, frees, and varying prompt lengths
    must not retrace (the fixed-shape XLA serving contract)."""
    model, params = _make("window_sinks")
    engine = LMEngine(model, params, max_slots=2, max_len=32, buckets=(4, 8))
    stats = engine.compile_stats()
    if stats["decode_compiles"] < 0:
        pytest.skip("this jax exposes no jit cache stats")
    sched = Scheduler(engine, max_queue=16)
    sched.generate_all([Request(prompt=[1, 2], max_new_tokens=3)])  # warmup
    warm = engine.compile_stats()
    assert warm["decode_compiles"] == 1
    rng = np.random.default_rng(0)
    reqs = [Request(prompt=list(rng.integers(0, 32, n)), max_new_tokens=6)
            for n in (1, 3, 4, 5, 7, 8, 2)]
    sched.generate_all(reqs)
    after = engine.compile_stats()
    assert after["decode_compiles"] == 1, "decode step recompiled mid-serve"
    assert after["insert_compiles"] == warm["insert_compiles"] == 1
    # one prefill program per bucket USED, not per prompt length
    used = {engine.pick_bucket(len(r.prompt)) for r in reqs}
    used.add(engine.pick_bucket(2))  # the warmup request
    assert after["prefill_compiles"] == len(used)


# slow tier: distributional sampling property (compiles its own
# engine); greedy parity and backpressure stay fast
@pytest.mark.slow
def test_temperature_sampling_reproducible_and_valid():
    """temperature>0 rides per-request key streams: same seed -> same
    stream, tokens stay in-vocab; different seeds diverge (eventually)."""
    model, params = _make("plain")

    def run(seed):
        engine = LMEngine(model, params, max_slots=2, max_len=32,
                          buckets=(4,))
        sched = Scheduler(engine)
        reqs = [Request(prompt=[1, 2], max_new_tokens=12, temperature=0.9,
                        seed=seed),
                Request(prompt=[3], max_new_tokens=12, temperature=0.9,
                        seed=seed + 1)]
        sched.generate_all(reqs)
        return [r.tokens for r in reqs]

    a, b = run(0), run(0)
    assert a == b, "same seeds must reproduce the same stream"
    assert all(0 <= t < 32 for toks in a for t in toks)
    assert run(123) != a, "different seeds should diverge"


def test_engine_validation():
    model, params = _make("plain")
    moe = lm_tiny(vocab=8, moe_every=1, num_experts=2, moe_fn=lambda *a: None)
    with pytest.raises(ValueError, match="dense"):
        LMEngine(moe, params, max_slots=1, max_len=8)
    nope, nparams = _make("plain", use_rope=False, max_len=8)
    with pytest.raises(ValueError, match="max_len"):
        LMEngine(nope, nparams, max_slots=1, max_len=16)
    # every bucket above max_len: the engine falls back to one
    # max_len-sized bucket rather than refusing all prompts
    eng = LMEngine(model, params, max_slots=1, max_len=16, buckets=(64,))
    assert eng.buckets == (16,)
