"""Self-healing training: in-run anomaly detection + bounded recovery.

PR 7 made runs survive *external* failure (SIGTERM → checkpoint →
elastic resume); this module defends the training loop against its own
steps.  A non-finite gradient, a loss spike or a wedged collective used
to either crash the run or silently burn the rest of the grant window —
on mixed/degraded fleets step-level anomalies are routine, not
exceptional (arXiv:2602.18007), so the loop needs detection plus
*bounded automatic* recovery.

Three pieces:

* **On-device sentinel** (:func:`..parallel.dp.guard_sentinel`, compiled
  into the train step by ``prepare_training(guard=True)``): a length-2
  f32 vector ``[poisoned_loss, grad_norm]`` — the global ``isfinite``
  any-reduce over loss + every gradient leaf folded into the first
  component (``0 * inf`` and ``0 * nan`` are both NaN), the global grad
  L2 norm in the second.  Cost: ONE extra device→host fetch per step,
  zero extra compiles after step 0.  Steps compiled without the
  sentinel degrade to a loss-only sentinel (``metrics["loss"]``): still
  catches non-finite loss and loss spikes, blind to a gradient blow-up
  that leaves the loss finite.
* **Host-side policy engine** (:class:`TrainGuard`): a rolling
  robust-z-score loss-spike detector (median/MAD — one slow eval or a
  legitimate big step cannot drag the baseline) feeding the policy
  ladder:

  1. **skip-and-quarantine** — the anomalous batch's loader item joins
     the quarantine set, the post-step state is discarded (the trainer
     holds the pre-step state, same recovery contract as OOM-skip:
     ``donate=False``), and the run continues;
  2. **rollback** — when anomalies persist inside a window
     (``rollback_after`` within ``anomaly_window`` items) the state
     itself is suspect: the trainer restores the last-good checkpoint,
     rewinds the data cursor, and replays with the quarantined span
     skipped — recorded in the RESUME manifest so a crash mid-replay
     resumes identically;
  3. **halt** — rollbacks recurring without ``progress_steps`` of clean
     work in between mean the run cannot make progress:
     :class:`GuardHalt` (``retryable=False``) ends it, and
     ``bin/driver.py`` exits with :data:`..faults.HALTED_RC` so a
     supervisor pages a human instead of requeueing.

* **Deterministic replay** (:func:`replay_item` / ``bin/driver.py
  --replay-step K``): loader batches are a pure function of
  ``(seed, process, item)``, so one quarantined step re-executes from
  checkpoint + cursor for diagnosis — under ``jax_debug_nans`` the
  producing primitive gets named.

Every decision lands in ``fdtpu_guard_*`` metrics; injection for tests
rides the :mod:`..faults` value sites (``train.loss`` / ``train.grad``
with ``nan``/``inf`` actions) — deterministic, RNG-free, recompile-free.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Optional, Sequence

import numpy as np

from .. import faults
from .logging import Logger, current_logger

__all__ = ["GuardConfig", "GuardHalt", "TrainGuard", "replay_item",
           "state_donated"]


def state_donated(state) -> bool:
    """True when ``state``'s param buffers were donated to a step and
    freed — THE recovery-blocking condition OOM-skip, the guard's
    discard path and :func:`replay_item` all check identically."""
    import jax

    leaves = jax.tree.leaves(state.params)
    return bool(leaves) and getattr(leaves[0], "is_deleted", lambda: False)()


class GuardHalt(RuntimeError):
    """The guard's terminal verdict: recovery is looping without
    progress (or rollback is needed with nothing to roll back to).
    ``retryable`` is False by construction — a supervisor must NOT
    requeue this run (``bin/driver.py`` maps it to
    :data:`..faults.HALTED_RC`)."""

    retryable = False

    def __init__(self, message: str, *, rollbacks: int = 0,
                 quarantined: Sequence[int] = ()):
        super().__init__(message)
        self.rollbacks = rollbacks
        self.quarantined = list(quarantined)


@dataclasses.dataclass
class GuardConfig:
    """Policy knobs for :class:`TrainGuard`.

    Attributes
    ----------
    window: rolling robust-statistics window (accepted losses) feeding
        the spike detector's median/MAD
    warmup: accepted samples required before spike detection arms (the
        first losses of a fresh run are a falling edge, not a baseline);
        non-finite detection is always armed
    zmax: robust z-score threshold — ``0.6745 * |x - median| / MAD``
        above it is a spike (8 ≈ "this loss is not from this run's
        distribution"; cadence jitter and eval-cycle wobble sit far
        below)
    rollback_after: anomalies within ``anomaly_window`` recent items
        that escalate skip → rollback
    anomaly_window: the "persist" window, in loader items
    max_rollbacks: rollbacks tolerated without an intervening
        ``progress_steps`` clean span; one more halts the run
    progress_steps: clean (non-anomalous) items that clear the rollback
        debt
    quarantine: loader items to skip from the start — how a clean run
        deterministically skips the batches another run quarantined
        (the loss-parity oracle), and how a resume replays decisions
        recorded in the manifest
    """

    window: int = 64
    warmup: int = 8
    zmax: float = 8.0
    rollback_after: int = 3
    anomaly_window: int = 16
    max_rollbacks: int = 2
    progress_steps: int = 32
    quarantine: Sequence[int] = ()

    def __post_init__(self):
        if self.window < 2:
            raise ValueError(f"window must be >= 2, got {self.window}")
        if self.warmup < 2:
            raise ValueError(f"warmup must be >= 2, got {self.warmup}")
        if self.zmax <= 0:
            raise ValueError(f"zmax must be > 0, got {self.zmax}")
        if self.rollback_after < 1:
            raise ValueError(
                f"rollback_after must be >= 1, got {self.rollback_after}")
        if self.anomaly_window < 1:
            raise ValueError(
                f"anomaly_window must be >= 1, got {self.anomaly_window}")
        if self.max_rollbacks < 0:
            raise ValueError(
                f"max_rollbacks must be >= 0, got {self.max_rollbacks}")


class TrainGuard:
    """Host-side policy engine over the per-step sentinel.

    The trainer calls :meth:`is_quarantined` before dispatching an item
    and :meth:`observe` on the step's metrics before committing the new
    state; ``observe`` returns one of ``"ok"`` / ``"skip"`` /
    ``"rollback"`` / ``"halt"`` and the trainer executes the verdict
    (it owns the state, the loader cursor and the checkpoint dir).
    Decisions are a pure function of replicated scalars, so every host
    of a multi-host run reaches the same verdict from the same step.
    """

    def __init__(self, config: Optional[GuardConfig] = None, *,
                 registry=None, logger: Optional[Logger] = None):
        from ..obs import get_registry

        self.config = config or GuardConfig()
        self.logger = logger or current_logger()
        reg = registry if registry is not None else get_registry()
        self._quarantined: set = {int(i) for i in self.config.quarantine}
        self._losses: deque = deque(maxlen=self.config.window)
        self._recent_anomalies: deque = deque()
        self._rollbacks = 0
        self._rollback_debt = 0
        self._good_since_rollback = 0
        #: decision ledger (JSON-able), newest last — the driver logs
        #: it and tests read it; bounded like the loss window
        self.events: deque = deque(maxlen=256)
        self._m_anomalies = reg.counter(
            "fdtpu_guard_anomalies_total",
            "anomalous train steps detected by the guard",
            labelnames=("kind",))
        self._m_quarantined = reg.counter(
            "fdtpu_guard_quarantined_total",
            "loader items quarantined (skipped and recorded) by the guard")
        self._m_replayed = reg.counter(
            "fdtpu_guard_replay_skips_total",
            "pre-step skips of already-quarantined items (rollback "
            "replays and resumed runs)")
        self._m_rollbacks = reg.counter(
            "fdtpu_guard_rollbacks_total",
            "rollbacks to the last-good checkpoint")
        self._m_halts = reg.counter(
            "fdtpu_guard_halts_total",
            "guard halts (rollback loop without progress; retryable=false)")
        self._g_quarantine = reg.gauge(
            "fdtpu_guard_quarantine_size", "items currently quarantined")
        self._g_z = reg.gauge(
            "fdtpu_guard_last_z",
            "robust z-score of the most recent observed loss (0 until "
            "the detector warms up)")
        self._g_gnorm = reg.gauge(
            "fdtpu_guard_grad_norm", "global grad L2 norm of the most "
            "recent step carrying the compiled sentinel")
        self._g_quarantine.set(len(self._quarantined))

    # -- quarantine bookkeeping ---------------------------------------
    def is_quarantined(self, item: int) -> bool:
        return int(item) in self._quarantined

    def quarantined_items(self) -> list:
        return sorted(self._quarantined)

    def quarantine(self, item: int) -> None:
        self._quarantined.add(int(item))
        self._m_quarantined.inc()
        self._g_quarantine.set(len(self._quarantined))

    def note_replayed_skip(self, item: int) -> None:
        """A pre-step skip of an already-quarantined item (the replay
        after a rollback, or a resumed run honoring the manifest)."""
        self._m_replayed.inc()
        self.events.append({"item": int(item), "decision": "replay_skip"})

    # -- the robust spike detector ------------------------------------
    def zscore(self, x: float) -> Optional[float]:
        """Robust z of ``x`` against the accepted-loss window, or None
        while the detector is warming up.  0.6745·(x−median)/MAD — the
        MAD-consistency constant makes it comparable to a normal z.
        A degenerate MAD (e.g. an alternating window, where more than
        half the deviations are exactly zero) falls back to the mean
        absolute deviation; a bit-constant window falls through to an
        epsilon scale, so the first genuinely different loss still
        registers."""
        if len(self._losses) < self.config.warmup:
            return None
        vals = np.asarray(self._losses, dtype=np.float64)
        med = float(np.median(vals))
        dev = np.abs(vals - med)
        scale = max(float(np.median(dev)), float(np.mean(dev)),
                    1e-9 * max(abs(med), 1.0))
        return 0.6745 * (x - med) / scale

    # -- the verdict ---------------------------------------------------
    def observe(self, item: int, metrics: dict,
                can_rollback: bool = True) -> str:
        """Classify one completed step and return the trainer's order:
        ``"ok"`` (commit the new state), ``"skip"`` (discard it, the
        item is quarantined), ``"rollback"`` (restore last-good
        checkpoint and rewind to it), ``"halt"`` (raise
        :class:`GuardHalt`).

        ``metrics["guard"]`` — the compiled sentinel ``[poisoned_loss,
        grad_norm]`` (stacked ``[K, 2]`` under the device loop) — is
        preferred; ``metrics["loss"]`` is the loss-only fallback.
        Reading it is THE per-step device sync the guard costs.
        ``can_rollback=False`` (no checkpoint dir / nothing saved yet)
        short-circuits the rollback tier to halt.
        """
        g = metrics.get("guard")
        sentinel_compiled = g is not None
        if g is None:
            g = metrics["loss"]
        arr = np.asarray(g, dtype=np.float64)
        if sentinel_compiled:
            rows = arr.reshape(-1, 2)
            losses = [float(r[0]) for r in rows]
            gnorms = [float(r[1]) for r in rows]
        else:
            losses = [float(v) for v in arr.reshape(-1)]
            gnorms = []
        # deterministic injection taps: the fault plan corrupts what
        # the guard OBSERVES (never the training state), so detection +
        # recovery are provable RNG-free and the "clean run that
        # skipped the same batch" oracle stays exact
        losses[0] = faults.fire_value("train.loss", losses[0], index=item)
        if gnorms:
            gnorms[0] = faults.fire_value("train.grad", gnorms[0], index=item)
            finite_g = [v for v in gnorms if math.isfinite(v)]
            if finite_g:
                self._g_gnorm.set(finite_g[-1])

        kind = None
        detail: dict = {}
        if not all(map(math.isfinite, losses + gnorms)):
            kind = "nonfinite"
            detail = {"loss": losses[0],
                      "grad_norm": gnorms[0] if gnorms else None}
        else:
            for v in losses:
                z = self.zscore(v)
                if z is not None:
                    self._g_z.set(z)
                if z is not None and abs(z) > self.config.zmax:
                    kind = "loss_spike"
                    detail = {"loss": v, "z": round(z, 2)}
                    break

        if kind is None:
            self._losses.extend(losses)
            self._good_since_rollback += 1
            if (self._rollback_debt
                    and self._good_since_rollback
                    >= self.config.progress_steps):
                self._rollback_debt = 0
            return "ok"

        self._m_anomalies.labels(kind=kind).inc()
        self.quarantine(item)
        self._recent_anomalies.append(int(item))
        self._good_since_rollback = 0
        lo = int(item) - self.config.anomaly_window
        while self._recent_anomalies and self._recent_anomalies[0] <= lo:
            self._recent_anomalies.popleft()
        persistent = len(self._recent_anomalies) >= self.config.rollback_after

        decision = "skip"
        if persistent:
            if self._rollback_debt >= self.config.max_rollbacks or (
                    not can_rollback):
                decision = "halt"
                self._m_halts.inc()
            else:
                decision = "rollback"
                self._rollbacks += 1
                self._rollback_debt += 1
                self._recent_anomalies.clear()
                self._m_rollbacks.inc()
        event = {"item": int(item), "decision": decision, "kind": kind,
                 **detail}
        self.events.append(event)
        self.logger.info(
            f"guard: {kind} anomaly at item {item} -> {decision} "
            f"({detail}; {len(self._quarantined)} quarantined, "
            f"{self._rollbacks} rollbacks)")
        return decision

    def halt(self, reason: str) -> GuardHalt:
        """Build the terminal error (the trainer raises it)."""
        return GuardHalt(
            f"{reason} — quarantined items "
            f"{self.quarantined_items()}, {self._rollbacks} rollback(s); "
            "NOT retryable: requeueing cannot make progress, inspect with "
            "bin/driver.py --replay-step <k>",
            rollbacks=self._rollbacks, quarantined=self.quarantined_items())

    def snapshot(self) -> dict:
        """JSON-able state summary (manifest / ledger / driver log)."""
        return {
            "quarantined_items": self.quarantined_items(),
            "rollbacks": self._rollbacks,
            "rollback_debt": self._rollback_debt,
            "events": list(self.events)[-8:],
        }


def replay_item(task, item: int, debug_nans: bool = True) -> dict:
    """Deterministically re-execute ONE loader item against the task's
    current state — the quarantine postmortem harness behind
    ``bin/driver.py --replay-step K``.

    Loader batches are a pure function of ``(seed, process, item)``, so
    the exact quarantined batch reassembles with no replay of the run;
    restore the last-good checkpoint first (``--resume``) to reproduce
    the state the anomaly was observed against.  Runs under
    ``jax_debug_nans`` by default, so a genuine NaN names its producing
    primitive.  The task's state is NOT mutated (the step's output is
    discarded), so diagnosis can never advance — or further corrupt —
    a run.  Returns a JSON-able report.
    """
    import jax

    if state_donated(task.state):
        raise ValueError(
            "replay_item needs a live state: this task donated its "
            "buffers — re-prepare with donate=False")
    if item < 0 or item >= len(task.loader):
        raise ValueError(
            f"item {item} outside this run's range [0, {len(task.loader)})")
    host = task.loader._make_item(item)
    batch = task.loader._put(host)
    report: dict = {"item": int(item),
                    "steps_per_call": int(getattr(task.loader, "chunk", 1)),
                    "state_step": int(task.state.step)}
    old_nans = bool(jax.config.jax_debug_nans)
    if debug_nans:
        jax.config.update("jax_debug_nans", True)
    try:
        _, metrics = task.step_fn(task.state, batch)
        jax.block_until_ready(metrics)
    except FloatingPointError as e:
        # jax_debug_nans re-ran op-by-op and named the primitive — the
        # diagnosis, not a harness failure
        report.update(finite=False, error=str(e)[:500])
        return report
    finally:
        if debug_nans:
            jax.config.update("jax_debug_nans", old_nans)
    g = metrics.get("guard")
    if g is not None:
        rows = np.asarray(g, dtype=np.float64).reshape(-1, 2)
        report.update(
            loss=[float(r[0]) for r in rows],
            grad_norm=[float(r[1]) for r in rows],
            finite=bool(np.isfinite(rows).all()),
            sentinel="compiled")
    else:
        losses = np.asarray(metrics["loss"], dtype=np.float64).reshape(-1)
        report.update(
            loss=[float(v) for v in losses],
            finite=bool(np.isfinite(losses).all()),
            sentinel="loss-only")
    return report
