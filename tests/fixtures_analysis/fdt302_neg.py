"""FDT302 negative: the copy-under-lock/render-after-release pattern —
the registry never calls into the scheduler while holding its lock, so
the graph has one direction only."""
import threading


class ToyRegistry:
    def __init__(self, sched=None):
        self._lock = threading.Lock()
        self._sched = sched

    def render_exposition(self):
        with self._lock:
            target = self._sched  # snapshot under the lock ...
        return target.scrape_queue_depth()  # ... call after release


class ToyScheduler:
    def __init__(self, registry):
        self._lock = threading.Lock()
        self._registry = registry

    def scrape_queue_depth(self):
        with self._lock:
            return 0

    def finish_request(self):
        with self._lock:
            depth = 0
        self._registry.render_exposition()
        return depth
