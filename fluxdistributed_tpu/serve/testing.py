"""Test/dev scaffolding for the serve stack: a deterministic pure-python
engine with the :class:`~.engine.LMEngine` driver surface.

The router's whole contract — failover, drain ordering, rolling
restarts — is about processes and sockets, not about attention math, so
its tests (and ``bin/serve.py --fake-engine`` replica fleets, and the CI
router smoke) run on :class:`FakeLMEngine`: no model, no compiles, a
scheduler tick costs ``step_delay`` seconds of sleep.

The token stream is a **pure function of the prompt**: the first token
is a digest of the prompt, every later token increments it (mod vocab).
That makes cross-replica determinism an assertable invariant — a request
transparently retried on a *different* replica after a mid-burst kill
must produce byte-identical output, exactly the property a greedy real
engine has and the router's zero-failed-requests guarantee rides on.
"""

from __future__ import annotations

import time
from typing import List

__all__ = ["FakeLMEngine", "fake_tokens"]


def fake_tokens(prompt, n: int, vocab: int = 256) -> List[int]:
    """The exact stream any :class:`FakeLMEngine` produces for
    ``prompt`` — the oracle router tests compare failover output
    against."""
    first = (sum(int(t) for t in prompt) + len(prompt)) % vocab
    return [(first + i) % vocab for i in range(n)]


class FakeLMEngine:
    """Deterministic slot engine (the :class:`~.scheduler.Scheduler`
    driver API, nothing else).

    ``step_delay`` is a plain mutable attribute: tests raise it
    mid-flight to simulate a replica that goes slow or wedges after its
    first tokens (the router's fail-fast-after-first-token path).
    """

    def __init__(self, max_slots: int = 4, max_len: int = 512,
                 step_delay: float = 0.0, vocab: int = 256):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.max_slots = max_slots
        self.max_len = max_len
        self.step_delay = step_delay
        self.vocab = vocab
        self._last = [0] * max_slots
        self._live = [False] * max_slots

    # -- the Scheduler driver surface ----------------------------------
    def validate_request(self, prompt_len: int, max_new_tokens: int) -> None:
        if prompt_len < 1:
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if prompt_len + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len={self.max_len}")

    def prefill(self, slot: int, prompt, temperature, key):
        first = fake_tokens(prompt, 1, self.vocab)[0]
        self._last[slot] = first
        self._live[slot] = True
        return first, len(prompt)  # (first token, "bucket" = real len)

    def step_decode(self):
        if self.step_delay:
            time.sleep(self.step_delay)
        out = []
        for s in range(self.max_slots):
            if self._live[s]:
                self._last[s] = (self._last[s] + 1) % self.vocab
            out.append(self._last[s])
        return out

    def reset_slot(self, slot: int) -> None:
        self._live[slot] = False
        self._last[slot] = 0

    def compile_stats(self) -> dict:
        # the shape the scheduler's compile gauges scrape; a fake engine
        # trivially satisfies the ONE-decode-compile invariant
        return {"decode_compiles": 1, "prefill_compiles": 1,
                "insert_compiles": 1}
