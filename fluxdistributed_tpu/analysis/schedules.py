"""Deterministic-schedule race harness — the dynamic half of the
FDT3xx concurrency layer.

Static rules (:mod:`analysis.concurrency`) *predict* interleavings;
this module *forces* them.  A :class:`SchedulePlan` interposes on a
live object's threading primitives (:func:`instrument` swaps the
``threading.Lock``/``RLock``/``Event`` instances in its ``__dict__``
for traced wrappers) and injects preemption — a sleep long enough for
every other runnable thread to race ahead — at chosen **lock
boundaries**: the k-th crossing of ``"<Type>.<attr>.acquire"``,
``".held"`` (just after acquisition) or ``".release"``.  Under CPython's
5 ms GIL switch interval a racy window of a few bytecodes essentially
never interleaves on its own; a forced preemption inside it manifests
the race on the first run, every run — the concurrency analogue of the
FDT2xx variant sweep, runnable over the real Scheduler / Router /
StepWatchdog / FlightRecorder objects with ``FakeLMEngine``.

Injection follows ``faults.py``'s factory-hook contract exactly (and is
FDT104-clean the same way): tests build a plan, ``install_schedule`` it,
run, ``clear_schedule`` in a ``finally``.  Instrumented objects call
the module-level :func:`cross` hook, which is a single global ``None``
check when no plan is installed — production code never pays for the
harness, and nothing ever mutates a global from trace-reachable code.

Reproducers: a plan serializes to JSON (:meth:`SchedulePlan.spec`), and
:func:`run_under_schedule` dumps that spec — seed, preemption table,
full crossing log — next to the obs artifacts when the function under
test fails, so a CI schedule failure ships its exact interleaving::

    plan = SchedulePlan(seed=7).preempt_at("Scheduler._lock.release",
                                           at=1, delay=0.05)
    instrument(sched)
    run_under_schedule(plan, lambda: hammer(sched))  # dumps on raise
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "Preemption",
    "SchedulePlan",
    "TracedEvent",
    "TracedLock",
    "active_schedule",
    "clear_schedule",
    "cross",
    "install_schedule",
    "instrument",
    "run_under_schedule",
]

#: concrete primitive types instrument() swaps (threading.Lock/RLock
#: are factory functions — the types only exist via construction)
_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))

#: env var naming a directory for schedule-failure reproducer JSON —
#: CI points it at the obs-artifacts dir so a failed harness run
#: uploads its exact interleaving
REPRO_DIR_ENV = "FDTPU_SCHEDULE_REPRO_DIR"


@dataclasses.dataclass
class Preemption:
    """Stall the crossing thread at the ``at``-th (1-based) crossing of
    ``site``, ``times`` consecutive crossings, ``delay`` seconds each —
    long enough for every other runnable thread to race past."""

    site: str
    at: int = 1
    times: int = 1
    delay: float = 0.05

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class SchedulePlan:
    """A seeded preemption schedule over lock-boundary crossings.

    Two modes compose:

    * **explicit** — :meth:`preempt_at` pins a stall to the k-th
      crossing of one site: the deterministic-reproduction mode tests
      assert with;
    * **seeded fuzz** — :meth:`fuzz` derives, per ``(site, count)``
      crossing identity, whether/how long to stall from a hash of the
      seed.  The same seed injects the same stalls at the same
      crossings regardless of wall clock — an exploration mode whose
      failures replay exactly.

    The plan is also the flight recorder of the run: every crossing is
    logged (site, per-site index, thread name, stall applied), and
    :meth:`spec` serializes seed + table + log as the reproducer JSON.
    """

    def __init__(self, seed: int = 0, max_log: int = 4096):
        self.seed = int(seed)
        self.max_log = int(max_log)
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._preempts: List[Preemption] = []
        self._fuzz: Optional[Tuple[float, float]] = None  # (prob, delay)
        self._log: List[dict] = []
        self._fired = 0

    # -- construction ---------------------------------------------------
    def preempt_at(self, site: str, at: int = 1, times: int = 1,
                   delay: float = 0.05) -> "SchedulePlan":
        if at < 1 or times < 1 or delay < 0:
            raise ValueError(
                f"need at>=1, times>=1, delay>=0; got {at}/{times}/{delay}")
        # plans are normally built before installation, but arming a
        # preemption mid-run must not race cross()'s table scan
        with self._lock:
            self._preempts.append(Preemption(site, at, times, float(delay)))
        return self

    def fuzz(self, prob: float = 0.25,
             delay: float = 0.005) -> "SchedulePlan":
        """Stall a seeded ``prob`` fraction of ALL crossings by
        ``delay`` — which crossings is a pure function of
        ``(seed, site, index)``, so a failing seed is its reproducer."""
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"prob must be in [0, 1], got {prob}")
        with self._lock:
            self._fuzz = (float(prob), float(delay))
        return self

    # -- the interposition hot path -------------------------------------
    def cross(self, site: str) -> None:
        # `hit` and `stall` are distinct: a delay=0 preemption still
        # fires — time.sleep(0) yields the GIL, the minimal preemption
        hit, stall = False, 0.0
        with self._lock:
            c = self._counts[site] = self._counts.get(site, 0) + 1
            for p in self._preempts:
                if p.site == site and p.at <= c < p.at + p.times:
                    hit, stall = True, max(stall, p.delay)
            if not hit and self._fuzz is not None:
                prob, delay = self._fuzz
                h = zlib.crc32(f"{self.seed}:{site}:{c}".encode())
                if (h % 10_000) < prob * 10_000:
                    hit, stall = True, delay
            if hit:
                self._fired += 1
            if len(self._log) < self.max_log:
                self._log.append({
                    "site": site, "n": c,
                    "thread": threading.current_thread().name,
                    "hit": hit, "stall": stall})
        if hit:
            time.sleep(stall)

    # -- introspection / reproducers ------------------------------------
    def crossings(self, site: Optional[str] = None) -> Any:
        with self._lock:
            if site is not None:
                return self._counts.get(site, 0)
            return dict(self._counts)

    @property
    def fired(self) -> int:
        """Preemptions actually injected — a harness run that asserts
        on a schedule should also assert this is non-zero, or the
        harness has silently become a no-op."""
        with self._lock:
            return self._fired

    def spec(self) -> dict:
        with self._lock:
            return {
                "schema": "fdtpu-schedule-repro/v1",
                "seed": self.seed,
                "preempt": [p.to_dict() for p in self._preempts],
                "fuzz": ({"prob": self._fuzz[0], "delay": self._fuzz[1]}
                         if self._fuzz else None),
                "fired": self._fired,
                "crossings": dict(self._counts),
                "log": list(self._log),
            }

    def dump(self, path: str) -> str:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as fh:
            json.dump(self.spec(), fh, indent=2)
            fh.write("\n")
        return path

    @classmethod
    def from_spec(cls, spec: dict) -> "SchedulePlan":
        plan = cls(seed=int(spec.get("seed", 0)))
        for p in spec.get("preempt") or []:
            plan.preempt_at(p["site"], at=int(p.get("at", 1)),
                            times=int(p.get("times", 1)),
                            delay=float(p.get("delay", 0.05)))
        fz = spec.get("fuzz")
        if fz:
            plan.fuzz(prob=float(fz["prob"]), delay=float(fz["delay"]))
        return plan


# -- the factory hook (faults.py contract: never a bare mutable global
# read from traced code — install/clear/active accessors only) ----------

_SCHEDULE: Optional[SchedulePlan] = None


def install_schedule(plan: SchedulePlan) -> SchedulePlan:
    global _SCHEDULE
    _SCHEDULE = plan
    return plan


def clear_schedule() -> None:
    global _SCHEDULE
    _SCHEDULE = None


def active_schedule() -> Optional[SchedulePlan]:
    return _SCHEDULE


def cross(site: str) -> None:
    """Schedule-point hook: one global ``None`` check when no plan is
    installed — the instrumented primitives cost nothing outside the
    harness."""
    plan = _SCHEDULE
    if plan is not None:
        plan.cross(site)


# -- traced primitives ---------------------------------------------------


class TracedLock:
    """A ``Lock``/``RLock`` that announces its boundaries: ``.acquire``
    before blocking, ``.held`` just after acquisition, ``.release``
    just after release — the three points a forced preemption can pry
    an atomicity assumption apart."""

    def __init__(self, inner: Any, site: str):
        self._inner = inner
        self.site = site

    def acquire(self, blocking: bool = True, timeout: float = -1):
        cross(f"{self.site}.acquire")
        got = self._inner.acquire(blocking, timeout)
        if got:
            cross(f"{self.site}.held")
        return got

    def release(self) -> None:
        self._inner.release()
        cross(f"{self.site}.release")

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


class TracedEvent:
    """A ``threading.Event`` announcing ``.set`` and ``.wait``
    completion — wake-up ordering is schedulable too."""

    def __init__(self, inner: threading.Event, site: str):
        self._inner = inner
        self.site = site

    def set(self) -> None:
        self._inner.set()
        cross(f"{self.site}.set")

    def wait(self, timeout: Optional[float] = None) -> bool:
        got = self._inner.wait(timeout)
        cross(f"{self.site}.wait")
        return got

    def clear(self) -> None:
        self._inner.clear()

    def is_set(self) -> bool:
        return self._inner.is_set()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


def instrument(obj: Any, site_prefix: Optional[str] = None) -> Any:
    """Swap every ``Lock``/``RLock``/``Event`` in ``obj.__dict__`` for
    a traced wrapper whose site is ``"<Type>.<attr>"`` — real objects
    (a live Scheduler, Router, StepWatchdog, FlightRecorder) join the
    harness with no source changes.  Idempotent; returns ``obj``."""
    prefix = site_prefix or type(obj).__name__
    for name, val in list(vars(obj).items()):
        if isinstance(val, (TracedLock, TracedEvent)):
            continue
        if isinstance(val, _LOCK_TYPES):
            setattr(obj, name, TracedLock(val, f"{prefix}.{name}"))
        elif isinstance(val, threading.Event):
            setattr(obj, name, TracedEvent(val, f"{prefix}.{name}"))
    return obj


def run_under_schedule(plan: SchedulePlan, fn: Callable[[], Any],
                       repro_name: str = "schedule-failure") -> Any:
    """Install ``plan``, run ``fn``, always clear.  If ``fn`` raises
    (an assertion caught a race, or the race corrupted state into a
    crash) the plan's reproducer JSON is written to
    ``$FDTPU_SCHEDULE_REPRO_DIR`` (when set) before re-raising — CI
    uploads the directory with the obs artifacts, so the exact failing
    interleaving ships with the red build."""
    install_schedule(plan)
    try:
        return fn()
    except BaseException:
        repro_dir = os.environ.get(REPRO_DIR_ENV)
        if repro_dir:
            try:
                stamp = f"{repro_name}-seed{plan.seed}.json"
                plan.dump(os.path.join(repro_dir, stamp))
            except OSError:
                pass  # reproducers are best-effort forensics
        raise
    finally:
        clear_schedule()
