"""Test harness: 8 virtual CPU devices.

The analog of the reference's fake-device story (test/single_device.jl:
121-151 — integer fake devices that work because ``@device!`` is a no-op
without CUDA): here the very same SPMD mesh code runs against
``--xla_force_host_platform_device_count=8`` CPU devices, so every
sharding/collective path is exercised on CI hardware.

Must run before any test initializes a JAX backend; this image's
sitecustomize imports jax at interpreter start, so the platform override
has to go through ``jax.config`` (which ``force_host_devices`` does).
"""

from fluxdistributed_tpu.mesh import force_host_devices

force_host_devices(8)
