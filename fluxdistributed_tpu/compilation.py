"""Cold-start performance subsystem: persistent compile cache + AOT
executables + warmup.

Every hardware benchmark round to date (BENCH_r01-r05) died inside XLA
cold-start compilation: the measurement itself takes seconds, but the
first process to touch the chip pays minutes of compilation before a
single step runs, and short TPU grant windows expire first.  The
compile-once/execute-many XLA contract (arXiv:1810.09868) means none of
that work is inherently per-process — this module makes it durable:

* :func:`enable_persistent_cache` — one call turns on JAX's persistent
  compilation cache (disk-backed, content-addressed by HLO + compile
  options), namespaced per topology so a CPU dev box and a TPU slice
  never collide in one directory.  Config-name differences across jax
  versions are absorbed by :func:`compat.configure_compilation_cache`
  (no-op with a warning, never a crash, on builds without the knobs).
* AOT helpers — :func:`aot_compile` (``lower → compile``),
  :func:`save_executable` / :func:`load_executable` (serialize the
  compiled XLA executable itself to disk, fingerprint-stamped), and
  :func:`load_or_compile` which falls back to a fresh compile whenever
  the topology/jaxlib fingerprint or argument signature mismatches.
  Where the persistent cache skips the *backend compile*, a serialized
  executable also skips tracing and lowering — the whole cold path.
* :func:`warmup_train` — run ONE donated dummy train step (fresh
  zero-filled buffers, the live state untouched) so every compile and
  allocator warm-up is paid before timing or traffic starts.  The serve
  side's analog is :meth:`LMEngine.warmup`.

Everything reports through the obs registry: AOT loads/compiles are
counters (``fdtpu_aot_loads_total`` / ``fdtpu_aot_compiles_total``) and
the cache's own hit/miss stream lands via :mod:`obs.jaxmon`.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import re
import time
from typing import Any, Optional, Sequence

from . import compat

__all__ = [
    "enable_persistent_cache",
    "persistent_cache_dir",
    "topology_fingerprint",
    "topology_namespace",
    "abstract_signature",
    "callable_tag",
    "config_tag",
    "aot_compile",
    "save_executable",
    "load_executable",
    "load_or_compile",
    "warmup_train",
    "compile_metrics",
]

#: format tag embedded in every serialized executable; bumping it
#: invalidates all on-disk executables at once (they fall back to a
#: fresh compile, never to a crash)
AOT_MAGIC = "fdtpu-aot-v1"

#: filename suffix for serialized executables
AOT_SUFFIX = ".jaxexec"

_cache_dir: Optional[str] = None


def topology_fingerprint(mesh=None, tag: str = "") -> str:
    """Digest of everything a serialized executable is specific to:
    jax/jaxlib versions, backend platform and device kind, device and
    process counts, optionally the mesh shape and a caller tag (e.g.
    the spmd mode knobs that change the compiled program without
    changing argument shapes).  Argument SHAPES are deliberately not
    here — :func:`abstract_signature` covers those, so the two compose
    into the on-disk key."""
    import jax
    import jaxlib

    dev = jax.devices()[0]
    parts = [
        jax.__version__,
        jaxlib.__version__,
        dev.platform,
        str(getattr(dev, "device_kind", "")),
        str(jax.device_count()),
        str(jax.process_count()),
    ]
    if mesh is not None:
        parts.append(repr(sorted(dict(mesh.shape).items())))
    if tag:
        parts.append(tag)
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def topology_namespace() -> str:
    """Human-readable per-topology subdirectory for the persistent
    cache: ``tpu-tpu-v5-lite-d8p1-jax0.4.37``.  jax's own cache key
    already covers all of this — the namespace exists so one shared
    cache root stays inspectable (which entries belong to which
    machine) and so an rsync of one topology's entries is possible."""
    import jax
    import jaxlib

    dev = jax.devices()[0]
    kind = re.sub(r"[^a-z0-9]+", "-", str(
        getattr(dev, "device_kind", "") or dev.platform).lower()).strip("-")
    return (f"{dev.platform}-{kind}-d{jax.device_count()}"
            f"p{jax.process_count()}-jax{jax.__version__}-{jaxlib.__version__}")


def enable_persistent_cache(
    cache_dir: Optional[str],
    *,
    min_entry_size_bytes: int = -1,
    min_compile_time_secs: float = 0.0,
    namespace: bool = True,
) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Returns the RESOLVED directory (namespaced per topology unless
    ``namespace=False``), or ``None`` when ``cache_dir`` is falsy or
    this jax build has no persistent cache (warned, never raised —
    the compat shim).  Thresholds default to "cache everything":
    ``min_entry_size_bytes=-1`` (jax's use-min-compile-time sentinel)
    and ``min_compile_time_secs=0.0`` — on TPU the compiles that matter
    are all multi-second, and on CPU (tests, smoke runs) the point is
    exactly the small entries jax's 1s default would skip.

    Call it BEFORE the first compile; when something already compiled,
    the enablement still takes effect for later compiles (jax's
    once-per-task cache-usage check is reset).
    """
    global _cache_dir
    if not cache_dir:
        return None
    path = os.path.abspath(os.path.expanduser(cache_dir))
    if namespace:
        path = os.path.join(path, topology_namespace())
    os.makedirs(path, exist_ok=True)
    if not compat.configure_compilation_cache(
            path, min_entry_size_bytes=min_entry_size_bytes,
            min_compile_time_secs=min_compile_time_secs):
        return None
    _cache_dir = path
    # surface enablement in the registry: a scrape answers "is this
    # process even using the cache" without reading logs
    from .obs import get_registry, jaxmon

    jaxmon.install()
    get_registry().gauge(
        "fdtpu_compile_cache_enabled",
        "1 when the persistent XLA compilation cache is configured",
    ).set(1)
    return path


def persistent_cache_dir() -> Optional[str]:
    """The resolved cache directory of the last successful
    :func:`enable_persistent_cache` call in this process (None when the
    cache was never enabled here)."""
    return _cache_dir


def abstract_signature(args: Sequence[Any], kwargs: Optional[dict] = None) -> str:
    """Digest of the tree structure + shapes/dtypes of a call's
    arguments — the part of an executable's identity the topology
    fingerprint does not cover.  Two calls with the same signature and
    fingerprint may share a serialized executable; anything else must
    not.  fdtpu-lint's FDT204 retrace check builds on this digest: a
    program whose trace moves under a fixed signature would break these
    on-disk keys on every restart (docs/analysis.md).

    Pallas interpret-mode note: the kernels resolve "interpreter or
    compiled" at TRACE time from the backend
    (``ops.pallas_attention.interpret_mode``) rather than taking an
    ``interpret`` argument, so the flag can never appear in this digest
    — CPU- and TPU-built executables are keyed apart by the PLATFORM
    field of :func:`topology_fingerprint` instead, which is the
    deliberate split (interpretation is a consequence of the platform,
    not an independent key axis)."""
    import jax

    leaves, treedef = jax.tree.flatten((tuple(args), kwargs or {}))

    def aval(x):
        shape = tuple(getattr(x, "shape", ()))
        dtype = str(getattr(x, "dtype", type(x).__name__))
        return f"{shape}:{dtype}"

    payload = str(treedef) + "|" + ";".join(aval(x) for x in leaves)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def callable_tag(fn, depth: int = 2) -> str:
    """Stable identity string for a configured callable: its name plus
    any scalar constants (and, one level down, callables) closed over —
    e.g. ``momentum(0.1, 0.9).update`` → ``update:0.1:0.9``.  This is
    what distinguishes two optimizers/losses whose hyperparameters are
    baked into the compiled program as constants without changing any
    argument shape.  Deliberately address-free: reprs of functions or
    objects (which embed ``0x...`` ids) never enter the tag, so the
    same configuration hashes identically across processes."""
    parts = [getattr(fn, "__name__", type(fn).__name__)]
    for cell in getattr(fn, "__closure__", None) or ():
        try:
            v = cell.cell_contents
        except ValueError:  # pragma: no cover — empty cell
            continue
        if isinstance(v, (bool, int, float, str, bytes, type(None))):
            parts.append(repr(v))
        elif isinstance(v, (tuple, frozenset)) and all(
                isinstance(e, (bool, int, float, str)) for e in v):
            parts.append(repr(v))
        elif callable(v) and depth > 0:
            parts.append(callable_tag(v, depth - 1))
    return ":".join(parts)


_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


def config_tag(*parts) -> str:
    """Digest arbitrary configuration parts into the short tag that
    feeds :func:`topology_fingerprint` — THE one place AOT key
    construction lives, shared by the trainer and the serve engine so
    the two cannot drift.  Callables route through :func:`callable_tag`;
    everything else stringifies with memory addresses scrubbed — a
    ``repr(model)`` whose ``attn_fn`` field prints ``<function ... at
    0x7f...>`` must hash identically across processes or on-disk
    executables are never reused."""
    norm = []
    for p in parts:
        if callable(p) and not isinstance(p, type):
            norm.append(callable_tag(p))
        else:
            norm.append(_ADDR_RE.sub("0x", str(p)))
    return hashlib.sha256("|".join(norm).encode()).hexdigest()[:12]


def aot_compile(fn, *args, **kwargs):
    """``lower → compile`` of a jitted callable at the given (concrete
    or ShapeDtypeStruct) arguments.  The result executes those argument
    shapes only — that is the point: it can be serialized."""
    if not hasattr(fn, "lower"):
        raise ValueError(
            f"{getattr(fn, '__name__', fn)!r} has no .lower — AOT "
            "compilation needs a jax.jit-wrapped callable")
    return fn.lower(*args, **kwargs).compile()


def save_executable(path: str, compiled, *, fingerprint: Optional[str] = None) -> str:
    """Serialize an AOT-compiled executable to ``path`` (atomic write).
    The file carries a format magic and the topology fingerprint;
    :func:`load_executable` refuses anything that does not match."""
    from jax.experimental.serialize_executable import serialize

    payload, in_tree, out_tree = serialize(compiled)
    blob = pickle.dumps({
        "magic": AOT_MAGIC,
        "fingerprint": fingerprint or topology_fingerprint(),
        "payload": payload,
        "in_tree": in_tree,
        "out_tree": out_tree,
    })
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    return path


def load_executable(path: str, *, fingerprint: Optional[str] = None):
    """Deserialize an executable saved by :func:`save_executable`.

    Returns ``None`` — never raises — on a missing/corrupt file, a
    format-magic mismatch, or a topology fingerprint mismatch: every
    load site falls back to a fresh compile, so a stale artifact can
    only ever cost the compile it failed to save."""
    from jax.experimental.serialize_executable import deserialize_and_load

    expected = fingerprint or topology_fingerprint()
    try:
        with open(path, "rb") as f:
            blob = pickle.loads(f.read())
        if blob.get("magic") != AOT_MAGIC or blob.get("fingerprint") != expected:
            return None
        return deserialize_and_load(
            blob["payload"], blob["in_tree"], blob["out_tree"])
    except Exception:  # noqa: BLE001 — any load failure means "recompile"
        return None


def load_or_compile(
    fn,
    args: Sequence[Any] = (),
    kwargs: Optional[dict] = None,
    *,
    directory: str,
    name: str,
    fingerprint: Optional[str] = None,
    save: bool = True,
    registry=None,
):
    """The AOT workflow in one call: look for a serialized executable of
    ``fn`` at these arguments under ``directory``, else lower + compile
    (and serialize the result for the next process).

    The on-disk key is ``<name>-<topology fp>-<argument signature>`` —
    a jaxlib upgrade, a different device count, or a shape change each
    select a different file, so a mismatch is an automatic miss, not a
    crash.  Outcomes are counted in the obs registry
    (``fdtpu_aot_loads_total`` / ``fdtpu_aot_compiles_total``) and the
    load/compile seconds accumulate in
    ``fdtpu_aot_seconds_total{source=...}``.
    """
    from .obs import get_registry

    reg = registry or get_registry()
    fp = fingerprint or topology_fingerprint()
    sig = abstract_signature(args, kwargs)
    path = os.path.join(directory, f"{name}-{fp}-{sig}{AOT_SUFFIX}")
    secs = reg.histogram(
        "fdtpu_aot_seconds_total",
        "wall seconds loading or compiling AOT executables",
        labelnames=("source",),
    )
    t0 = time.perf_counter()
    compiled = load_executable(path, fingerprint=fp)
    if compiled is not None:
        reg.counter(
            "fdtpu_aot_loads_total",
            "AOT executables deserialized from disk (compile skipped)",
        ).inc()
        secs.labels(source="load").observe(time.perf_counter() - t0)
        return compiled
    t0 = time.perf_counter()
    compiled = aot_compile(fn, *args, **(kwargs or {}))
    reg.counter(
        "fdtpu_aot_compiles_total",
        "AOT executables compiled fresh (no matching serialized file)",
    ).inc()
    secs.labels(source="compile").observe(time.perf_counter() - t0)
    if save:
        try:
            save_executable(path, compiled, fingerprint=fp)
        except Exception as e:  # noqa: BLE001 — serialization is best-effort
            import sys

            print(f"compilation: could not serialize {name!r} to {path}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
    return compiled


def _sharded_zeros_like(tree):
    """Fresh zero-filled buffers with the SAME shardings as ``tree``,
    assembled shard-by-shard: a model whose state only fits sharded
    never materializes a dense copy on one device, and mixed device
    sets across leaves (a replicated param tree next to a
    single-device step counter) are fine — each leaf is built
    independently."""
    import jax
    import numpy as np

    def shard_shape(shape, idx):
        out = list(shape)
        for d, sl in enumerate(idx):
            start, stop, _ = sl.indices(shape[d])
            out[d] = max(0, stop - start)
        return tuple(out)

    def zeros(x):
        if not isinstance(x, jax.Array):
            return x
        return jax.make_array_from_callback(
            x.shape, x.sharding,
            lambda idx: np.zeros(shard_shape(x.shape, idx), dtype=x.dtype))

    return jax.tree.map(zeros, tree)


def warmup_train(task, batch, *, eval_too: bool = True) -> dict:
    """Pre-pay the training cold start: run ONE optimizer step on
    donated dummy inputs (zero-filled copies with the live state's
    shardings — the real :class:`TrainState` is never touched, so this
    composes with ``donate=True`` steps) and block until it lands.

    ``batch`` must have the exact layout training will feed (the
    trainer's ``prepare_training(warmup=True)`` builds it from the
    dataset).  With ``eval_too`` the compiled eval step warms up
    against the task's val batch when one exists.

    Returns ``{"seconds": ..., "compiles": ..., "compile_seconds": ...}``
    — what the cold start actually cost, so callers can log it against
    the steps it saves.
    """
    import jax

    from .obs import jaxmon

    jaxmon.install()
    c0, s0 = jaxmon.compile_count(), jaxmon.compile_seconds()
    t0 = time.perf_counter()
    dummy_state = _sharded_zeros_like(task.state)
    out = task.step_fn(dummy_state, batch)
    jax.block_until_ready(jax.tree.leaves(out))
    if eval_too and task.val_batch is not None:
        # the dummy state was (possibly) donated to the step above —
        # eval gets its own fresh zeros
        ev = task.eval_fn(_sharded_zeros_like(task.state), task.val_batch)
        jax.block_until_ready(jax.tree.leaves(ev))
    return {
        "seconds": time.perf_counter() - t0,
        "compiles": jaxmon.compile_count() - c0,
        "compile_seconds": jaxmon.compile_seconds() - s0,
    }


def compile_metrics() -> dict:
    """The cold-start ledger of this process, from the jaxmon counters:
    compile count/seconds plus persistent-cache hits/misses and the
    compile seconds the cache saved.  The bench harness embeds this in
    its JSON line (success AND timeout paths) so a dead round says
    whether the time went to compilation or to the hardware."""
    from .obs import jaxmon

    jaxmon.install()
    return {
        "compiles": int(jaxmon.compile_count()),
        "compile_seconds": round(jaxmon.compile_seconds(), 3),
        "cache_hits": int(jaxmon.cache_hits()),
        "cache_misses": int(jaxmon.cache_misses()),
        "compile_seconds_saved": round(jaxmon.compile_seconds_saved(), 3),
    }
