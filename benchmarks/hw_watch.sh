#!/bin/sh
# Unattended availability watcher (round-4 workflow, docs/benchmarks.md):
# keep attempting the headline measurement; the FIRST success proves the
# chip is granting, after which the FULL staged session runs
# (benchmarks/hw_session.sh).  Survives the driver's turn boundaries via
# nohup; one TPU client at a time is preserved by (a) waiting for any
# pre-existing bench process and (b) an flock on this script's lockfile.
#
#   nohup sh benchmarks/hw_watch.sh >> benchmarks/hw/watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
OUT="${1:-benchmarks/hw}"
mkdir -p "$OUT"
LOCK="$OUT/.watch.lock"
exec 9> "$LOCK"
if ! flock -n 9; then
    echo "watch: another watcher holds $LOCK; exiting"
    exit 0
fi
stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }

# Persistent XLA compilation cache, shared across attempts AND watcher
# restarts: attempt N+1 reads attempt N's compiles from disk instead of
# redoing them inside the grant window (bench.py enables the cache from
# this env var; an already-set value is respected).
: "${FDTPU_COMPILE_CACHE_DIR:=$OUT/xla_cache}"
export FDTPU_COMPILE_CACHE_DIR
mkdir -p "$FDTPU_COMPILE_CACHE_DIR"

# ADAPTIVE ATTEMPT BOUND: the straddle margin below used to assume the
# worst-case 2400 s for every attempt, so late in the window the
# watcher refused attempts that would easily have fit.  A completed
# attempt records its real compile+measure duration; the next bound is
# 2x that + 300 s slack (clamped to [600, 2400]) — with a warm compile
# cache the observed duration collapses, the bound follows, and more
# attempts fit before the deadline.
BOUND_CAP=2400
ATTEMPT_BOUND=$BOUND_CAP
_last=$(cat "$OUT/.last_attempt_secs" 2>/dev/null || true)
case "$_last" in
    ''|*[!0-9]*) ;;
    *)
        ATTEMPT_BOUND=$(( _last * 2 + 300 ))
        [ "$ATTEMPT_BOUND" -lt 600 ] && ATTEMPT_BOUND=600
        [ "$ATTEMPT_BOUND" -gt "$BOUND_CAP" ] && ATTEMPT_BOUND=$BOUND_CAP
        echo "[$(stamp)] watch: attempt bound ${ATTEMPT_BOUND}s (last observed ${_last}s)"
        ;;
esac

# HARD DEADLINE: the driver runs the official bench.py at round end,
# and the axon runtime grants ONE client at a time — a watcher attempt
# still holding (or queued for) the grant at that moment would wedge
# the official artifact even on a healthy chip.  An attempt is only
# launched if its full ATTEMPT_BOUND FITS before the deadline, so the
# slot is guaranteed free at the deadline itself.  Also honors a
# benchmarks/hw/.stop kill file.  Default: 8 h from watcher START
# (computed before the wait-for-in-flight loop, which can itself take
# a while); override with WATCH_DEADLINE_EPOCH.
DEADLINE="${WATCH_DEADLINE_EPOCH:-$(( $(date +%s) + 8 * 3600 ))}"

# a stop request or an already-unreachable deadline exits BEFORE the
# wait-for-in-flight loop: with a wedged client in flight, waiting
# first would delay (or swallow) an exit that needs no waiting at all
if [ -e "$OUT/.stop" ]; then
    echo "[$(stamp)] watch: stop file present; exiting"
    exit 0
fi
if [ "$(date +%s)" -ge "$(( DEADLINE - ATTEMPT_BOUND ))" ]; then
    echo "[$(stamp)] watch: no attempt fits before the deadline; exiting"
    exit 0
fi

# wait for any in-flight TPU client (grant contention wedges init);
# covers bench.py, every hw_session stage, manually launched
# benchmarks/*.py and bin/*.py clients — by any path (absolute,
# repo-relative, cwd-relative) and through interpreter flags
# ("python -u script.py") or module form ("python -m pkg.mod").
# Detection extracts the actual SCRIPT token of each live python
# interpreter and compares its basename against the script sets
# derived from benchmarks/ and bin/ at startup, so (a) a shell,
# editor, or pytest run whose argv merely mentions these names never
# counts, and (b) new benchmark scripts are covered without editing
# this list.  The .stop kill file is honored in the wait loop too, or
# a wedged client would make the watcher ignore stop requests forever
tpu_client_inflight() {
    # rebuilt each call (runs once/min) so scripts added mid-watch count
    _known="bench.py $(ls benchmarks/*.py bin/*.py 2>/dev/null | sed 's|.*/||' | tr '\n' ' ')"
    for _pid in $(pgrep -f "^([^ ]*/)?python[0-9.]*( |$)" 2>/dev/null); do
        _args=$(ps -o args= -p "$_pid" 2>/dev/null) || continue
        # first non-flag token after the interpreter = the script;
        # "-m pkg.mod" maps to pkg/mod.py so module launches count too;
        # -W/-X/-Q consume a separate argument, skip it
        _script=""
        _want_mod=0
        _skip=0
        set -- $_args
        shift
        for _tok in "$@"; do
            if [ "$_skip" = 1 ]; then _skip=0; continue; fi
            if [ "$_want_mod" = 1 ]; then
                _script="$(printf %s "$_tok" | tr '.' '/').py"
                break
            fi
            case "$_tok" in
                -m) _want_mod=1 ;;
                -W|-X|-Q|--check-hash-based-pycs) _skip=1 ;;
                -c) break ;;
                -*) ;;
                *) _script="$_tok"; break ;;
            esac
        done
        [ -n "$_script" ] || continue
        # CPU-pinned runs (test-suite driver children, --platform cpu
        # smoke benches) never hold the TPU grant
        case " $_args" in
            *" --platform cpu"*|*"--platform=cpu"*) continue ;;
        esac
        _base="${_script##*/}"
        case "$_base" in
            test_*|conftest.py) continue ;;        # pytest files never hold the grant
        esac
        for _k in $_known; do
            [ "$_base" = "$_k" ] && return 0
        done
    done
    return 1
}
while tpu_client_inflight; do
    if [ -e "$OUT/.stop" ]; then
        echo "[$(stamp)] watch: stop file present while waiting; exiting"
        exit 0
    fi
    # a long-lived matched client (e.g. bin/serve.py) must not make the
    # watcher outlive its deadline while holding the flock
    if [ "$(date +%s)" -ge "$(( DEADLINE - ATTEMPT_BOUND ))" ]; then
        echo "[$(stamp)] watch: deadline reached while waiting; exiting to free the slot"
        exit 0
    fi
    echo "[$(stamp)] watch: waiting for in-flight bench client"
    sleep 60
done

# EXPONENTIAL BACKOFF on availability failures (replaces the old fixed
# 120 s probe / 300 s attempt sleeps): consecutive backend-unavailable
# outcomes double the pause 60 s -> 960 s cap — a long outage is polled
# gently, while any sign of progress (probe success, a warmed attempt)
# resets to 60 s so a fresh grant window is exploited immediately.
# NON-retryable errors (bench.py's phase-aware "retryable": false —
# a real code failure, not the chip) stop the loop outright: hammering
# the queue cannot fix those and only burns grant windows.
BACKOFF=60
BACKOFF_CAP=960
backoff_sleep() {
    echo "[$(stamp)] watch: backing off ${BACKOFF}s"
    sleep "$BACKOFF"
    BACKOFF=$(( BACKOFF * 2 ))
    [ "$BACKOFF" -gt "$BACKOFF_CAP" ] && BACKOFF=$BACKOFF_CAP
}

attempt=0
while :; do
    if [ -e "$OUT/.stop" ]; then
        echo "[$(stamp)] watch: stop file present; exiting"
        exit 0
    fi
    # probe bound (120) + full attempt bound: the bench launch can
    # trail the loop-top check by a whole probe
    if [ "$(date +%s)" -ge "$(( DEADLINE - 120 - ATTEMPT_BOUND ))" ]; then
        echo "[$(stamp)] watch: attempt would straddle the deadline; exiting to free the slot"
        exit 0
    fi
    attempt=$((attempt + 1))
    # cheap bounded pre-probe: a ~2-min jax.devices() ping answers "is
    # the chip granting AT ALL?" before committing a full bench bound.
    # Dead attempts cost ~2 min instead of the full timeout, so short
    # grant windows are probed often; the full attempt launches only on
    # probe success (and must still fit the deadline on its own).
    echo "[$(stamp)] watch: probe attempt $attempt (120s jax.devices ping)"
    if ! timeout -k 10 120 python -c 'import jax; print(jax.devices())' \
            >> "$OUT/watch.err" 2>&1; then
        echo "[$(stamp)] watch: probe $attempt found no granting chip"
        backoff_sleep
        continue
    fi
    BACKOFF=60  # the chip is granting: poll eagerly again
    echo "[$(stamp)] watch: probe $attempt SUCCESS; launching resumable bench attempt (bound ${ATTEMPT_BOUND}s)"
    _t0=$(date +%s)
    # the resumable state machine makes every attempt's progress
    # durable: attempt N warms the compile cache + serializes the AOT
    # executable, attempt N+1 measures a handful of steps off them —
    # the first green number no longer needs one attempt to survive
    # the whole cold start inside one grant window
    timeout "$ATTEMPT_BOUND" python bench.py --resumable \
        --ledger "$OUT/resumable.json" --budget $(( ATTEMPT_BOUND - 60 )) \
        > "$OUT/.try.json" 2>> "$OUT/watch.err"
    rc=$?
    if [ "$rc" = 0 ] && grep -q '"warmed": true' "$OUT/.try.json" 2>/dev/null; then
        echo "[$(stamp)] watch: attempt $attempt WARMED the caches; measuring next"
        cat "$OUT/.try.json" >> "$OUT/bench.jsonl"
        continue  # progress, not failure: no backoff
    fi
    if [ "$rc" = 0 ] && ! grep -q '"error"' "$OUT/.try.json" 2>/dev/null \
            && grep -q '"value"' "$OUT/.try.json" 2>/dev/null; then
        echo "[$(stamp)] watch: SUCCESS on attempt $attempt"
        # record the observed compile+measure duration: it informs the
        # NEXT attempt bound (this watcher run and restarts alike)
        echo $(( $(date +%s) - _t0 )) > "$OUT/.last_attempt_secs"
        cat "$OUT/.try.json" >> "$OUT/bench.jsonl"
        cat "$OUT/.try.json"
        break
    fi
    echo "[$(stamp)] watch: attempt $attempt failed rc=$rc ($(tail -c 200 "$OUT/watch.err" | tr '\n' ' '))"
    if grep -q '"retryable": false' "$OUT/.try.json" 2>/dev/null; then
        echo "[$(stamp)] watch: NON-RETRYABLE failure (see $OUT/.try.json); stopping — fix the code, not the chip"
        cat "$OUT/.try.json" >> "$OUT/bench.jsonl"
        exit 1
    fi
    if [ "$rc" = 124 ] && [ "$ATTEMPT_BOUND" -lt "$BOUND_CAP" ]; then
        # the warm-derived bound killed a (re-)cold attempt — e.g. a
        # jaxlib upgrade rotated the compile-cache namespace.  Forget
        # the stale duration or every retry and every watcher restart
        # reuses the too-small bound forever
        echo "[$(stamp)] watch: attempt hit the adaptive bound; resetting to ${BOUND_CAP}s"
        rm -f "$OUT/.last_attempt_secs"
        ATTEMPT_BOUND=$BOUND_CAP
    fi
    backoff_sleep
done

# chip is granting: run the rest of the staged chain (stage 1 re-runs
# bench.py, giving the required second reproduction of the headline) —
# but only with >= 2 h of runway, and only if no stop was requested
# while the last attempt ran.  The 2 h gate alone cannot bound the
# whole chain (the stages' summed worst-case timeouts far exceed it),
# so the deadline is EXPORTED: hw_session checks it before each stage
# and step_sweep between children — the kill-free safe points — and
# they skip whatever no longer fits.
if [ -e "$OUT/.stop" ]; then
    echo "[$(stamp)] watch: stop file present; keeping only the captured bench row"
    exit 0
fi
if [ $(( DEADLINE - $(date +%s) )) -lt 7200 ]; then
    echo "[$(stamp)] watch: <2h to deadline; keeping only the captured bench row"
    exit 0
fi
echo "[$(stamp)] watch: launching full hw_session (deadline $(date -u -d "@$DEADLINE" +%H:%MZ 2>/dev/null || echo "$DEADLINE"))"
HW_DEADLINE_EPOCH="$DEADLINE" sh benchmarks/hw_session.sh "$OUT"
echo "[$(stamp)] watch: hw_session complete"
