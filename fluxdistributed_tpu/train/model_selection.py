"""Model-selection "parallelism" — the reference's legacy per-worker trainer.

Re-implements component #13 (SURVEY §2): ``run_distributed`` in
src/test.jl trains one independent replica per worker (``distribute``
:26-41, no gradient averaging), and after each cycle picks the replica
with the LOWEST validation loss as the next round's model for everyone
(:58) — model selection instead of grad sync — dividing the LR by 5
every 10 cycles (:50).

TPU-native design: replicas live as ONE stacked pytree with a leading
replica axis sharded over the mesh's data axis, so "N independent
trainers" is a single ``vmap``-ed compiled step — no tasks, no worker
processes.  Selection (eval → argmin → broadcast-best) is also compiled:
``jnp.take`` along the replica axis followed by re-broadcast, which XLA
lowers to one all-gather-style collective.  The dead reference path
becomes a live, tested feature.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import mesh as mesh_lib
from ..ops import logitcrossentropy, onehot
from ..optim import Optimizer
from ..parallel.dp import flax_loss_fn
from .logging import Logger, current_logger

Pytree = Any

__all__ = ["SelectionTask", "prepare_model_selection", "train_model_selection"]


@dataclasses.dataclass
class SelectionTask:
    params: Pytree  # stacked (R, ...) leaves, sharded on the replica axis
    opt_state: Pytree
    model_state: Pytree
    dropout_keys: Pytree  # (R, ...) per-replica dropout streams
    step_fn: Callable
    select_fn: Callable
    mesh: Mesh
    model: Any
    replicas: int


def prepare_model_selection(
    model,
    optimizer: Optimizer,
    *,
    mesh: Optional[Mesh] = None,
    replicas: Optional[int] = None,
    loss: Callable = logitcrossentropy,
    input_shape=(32, 32, 3),
    seed: int = 0,
) -> SelectionTask:
    """Stack R independently-trained replicas and compile the two steps.

    Unlike the reference (identical init broadcast from process 1,
    src/test.jl:28), each replica gets its OWN init key — the ensemble
    explores different basins, which is the point of selection training.
    """
    mesh = mesh or mesh_lib.data_mesh()
    axis = mesh_lib.DATA_AXIS
    r = replicas or mesh.shape[axis]
    if r % mesh.shape[axis] != 0:
        raise ValueError(f"replicas ({r}) must divide over mesh axis {mesh.shape[axis]}")

    dummy = np.zeros((1, *input_shape), np.float32)
    keys = jax.random.split(jax.random.PRNGKey(seed), r)
    # Independent per-replica dropout streams (distinct from the init
    # keys): each replica must draw its own masks, or the ensemble's
    # "independent basin exploration" rationale collapses.
    dropout_keys = jax.random.split(jax.random.fold_in(jax.random.PRNGKey(seed), 1), r)

    def init_one(key):
        p_rng, d_rng = jax.random.split(key)
        variables = model.init({"params": p_rng, "dropout": d_rng}, dummy, train=True)
        params = variables["params"]
        mstate = {k: v for k, v in variables.items() if k != "params"}
        return params, optimizer.init(params), mstate

    params, opt_state, model_state = jax.vmap(init_one)(keys)
    rep = NamedSharding(mesh, P(axis))  # replica-axis sharding
    params, opt_state, model_state, dropout_keys = jax.device_put(
        (params, opt_state, model_state, dropout_keys), rep
    )

    loss_fn = flax_loss_fn(model, loss)

    def one_step(params, opt_state, mstate, batch, step, key):
        def lossf(p):
            rng = jax.random.fold_in(key, step)
            return loss_fn(p, mstate, batch, True, rng=rng)

        (l, (new_mstate, _)), grads = jax.value_and_grad(lossf, has_aux=True)(params)
        new_params, new_opt = optimizer.apply(params, grads, opt_state, step)
        return new_params, new_opt, new_mstate, l

    # vmap over the stacked replica axis: R independent training steps in
    # one compiled program (the ``asyncmap`` over workers, src/test.jl:33).
    # The per-replica dropout key is vmapped in so replicas draw
    # independent masks.
    vstep = jax.vmap(one_step, in_axes=(0, 0, 0, 0, None, 0))
    step_fn = jax.jit(vstep)

    def select(params, opt_state, mstate, val_batch):
        def eval_one(p, ms):
            l, _ = loss_fn(p, ms, val_batch, False)
            return l

        losses = jax.vmap(eval_one)(params, mstate)
        best = jnp.argmin(losses)  # min-val-loss replica, src/test.jl:58

        def bcast(x):
            return jnp.broadcast_to(x[best][None], x.shape)

        return (
            jax.tree.map(bcast, params),
            jax.tree.map(bcast, opt_state),
            jax.tree.map(bcast, mstate),
            losses,
        )

    select_fn = jax.jit(select)

    return SelectionTask(
        params=params,
        opt_state=opt_state,
        model_state=model_state,
        dropout_keys=dropout_keys,
        step_fn=step_fn,
        select_fn=select_fn,
        mesh=mesh,
        model=model,
        replicas=r,
    )


def train_model_selection(
    task: SelectionTask,
    dataset,
    val_batch: dict,
    *,
    cycles: int = 10,
    steps_per_cycle: int = 1,
    batch_size_per_replica: int = 8,
    seed: int = 0,
    logger: Optional[Logger] = None,
):
    """Run the select-the-best loop (``run_distributed`` src/test.jl:43-63).

    Each cycle: every replica trains ``steps_per_cycle`` steps on its own
    random sample (the per-worker ``tmp`` loop :13-24), then the
    min-val-loss replica is broadcast to all (:58).  LR scheduling is the
    optimizer's business — pass ``optim.step_decay(lr0, 0.2, every=10)``
    to reproduce the reference's LR/5-every-10 (:50).

    Returns host copies of the (identical) selected replica's params and
    the per-cycle selection-loss history.
    """
    logger = logger or current_logger()
    rng = np.random.default_rng(seed)
    r = task.replicas
    history = []
    step = jnp.zeros((), jnp.int32)
    for cycle in range(cycles):
        for _ in range(steps_per_cycle):
            imgs, labels = dataset.batch(rng, r * batch_size_per_replica)
            batch = {
                "image": jnp.asarray(imgs).reshape(r, batch_size_per_replica, *imgs.shape[1:]),
                "label": onehot(
                    jnp.asarray(labels).reshape(r, batch_size_per_replica),
                    dataset.nclasses,
                ),
            }
            batch = jax.device_put(
                batch, NamedSharding(task.mesh, P(mesh_lib.DATA_AXIS))
            )
            task.params, task.opt_state, task.model_state, train_losses = task.step_fn(
                task.params, task.opt_state, task.model_state, batch, step,
                task.dropout_keys,
            )
            step = step + 1
        task.params, task.opt_state, task.model_state, val_losses = task.select_fn(
            task.params, task.opt_state, task.model_state, val_batch
        )
        val_losses = np.asarray(val_losses)
        history.append(val_losses)
        logger.log(
            {
                "selection_best_loss": float(val_losses.min()),
                "selection_best_replica": int(val_losses.argmin()),
                "selection_mean_loss": float(val_losses.mean()),
            },
            cycle,
        )
    from .. import tree as tree_lib

    best_params = jax.tree.map(lambda x: x[0], task.params)
    return tree_lib.to_host(best_params), history
