"""FDT106 negative: convention-conforming (or out-of-scope) names."""


def _suffix():
    return "fdtpu_dynamic_total"


def register(reg):
    reg.counter("fdtpu_serve_requests_total")
    reg.gauge("fdtpu_queue_depth")
    reg.histogram("fdtpu_train_step_seconds")
    reg.counter(_suffix())  # non-literal first arg: out of scope
