"""FDT101 positive: Python control flow on a traced parameter's VALUE."""
import jax


@jax.jit
def relu_branchy(x):
    if x > 0:  # branches on the tracer — frozen at trace time
        return x
    return 0 * x


@jax.jit
def drain(x, steps):
    while steps > 0:  # tracer-valued loop condition
        x = x * x
        steps = steps - 1
    return x
