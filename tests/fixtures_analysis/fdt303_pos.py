"""FDT303 positive: a network round-trip and an unbounded join run
inside the lock region — every other thread needing the lock stalls
behind a remote peer."""
import threading
import urllib.request


class Prober:
    def __init__(self):
        self._lock = threading.Lock()
        self.status = {}

    def probe(self, url, worker):
        with self._lock:
            resp = urllib.request.urlopen(url)  # network under the lock
            worker.join()  # unbounded wait under the lock
            self.status[url] = resp.status
