"""Finding / severity / baseline machinery for the fdtpu-lint suite.

A :class:`Finding` is one detected hazard: rule id, severity, location
(``file:line``), a one-line message, and a fix hint.  Findings also
carry a ``detail`` key — a short, *stable* identifier (a function name,
an axis literal, a variant name) used for baseline matching instead of
the line number, so a checked-in allowlist survives unrelated edits to
the same file.

The baseline workflow (GSPMD-style "correctness as compile-time
metadata", arXiv:2004.13336, applied to the lint layer itself):

* ``analysis/baseline.json`` allowlists the findings that existed when
  the suite landed (or that are reviewed-and-accepted);
* ``bin/lint.py --check`` fails on any finding NOT in the baseline —
  new hazards fail CI from day one without demanding a flag-day fix of
  every historical one;
* fixing a finding and shrinking the baseline is always safe: stale
  baseline entries are reported (not fatal) so the allowlist ratchets
  toward empty.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "SEVERITIES",
    "Finding",
    "severity_rank",
    "format_finding",
    "load_baseline",
    "save_baseline",
    "baseline_key",
    "diff_findings",
    "summarize",
]

#: ordered low → high; ``--check`` fails on any NEW finding regardless
#: of severity, but reports and summaries sort by it
SEVERITIES = ("info", "warning", "error")


def severity_rank(sev: str) -> int:
    try:
        return SEVERITIES.index(sev)
    except ValueError:
        return len(SEVERITIES)  # unknown sorts worst — fail loudly


@dataclasses.dataclass(frozen=True)
class Finding:
    """One detected hazard.  ``detail`` is the stable baseline key part
    (see module docstring); ``hint`` is the actionable fix."""

    rule: str
    severity: str
    file: str
    line: int
    message: str
    hint: str = ""
    detail: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def format_finding(f: Finding, hint: bool = True) -> str:
    """``file:line: severity [RULE] message`` — the grep-able report
    line (rule id + file:line is what the acceptance gate and CI logs
    key on)."""
    s = f"{f.file}:{f.line}: {f.severity} [{f.rule}] {f.message}"
    if hint and f.hint:
        s += f"\n    hint: {f.hint}"
    return s


def baseline_key(f: Finding) -> Tuple[str, str, str]:
    """Line-number-free identity: (rule, file, detail).  Two findings of
    one rule in one file need distinct ``detail`` values to be
    individually baselined — rules set it to the offending symbol."""
    return (f.rule, f.file.replace(os.sep, "/"), f.detail)


def load_baseline(path: str) -> List[dict]:
    """The checked-in allowlist: a JSON list of ``{"rule", "file",
    "detail", ...}`` entries (extra keys like ``note`` are carried but
    ignored for matching).  A missing file is an empty baseline."""
    if not path or not os.path.exists(path):
        return []
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, list):
        raise ValueError(
            f"baseline {path} must be a JSON list of entries, got "
            f"{type(data).__name__}")
    return data


def save_baseline(path: str, findings: Sequence[Finding],
                  keep: Iterable[dict] = ()) -> None:
    """Write the allowlist: the given findings plus any ``keep`` entries
    (prior baseline entries a partial-scope scan could not have
    re-observed — a scoped ``--update-baseline`` must not silently erase
    the rest of the allowlist).  Deduplicated on the baseline key."""
    entries = [
        {"rule": f.rule, "file": f.file.replace(os.sep, "/"),
         "detail": f.detail, "message": f.message}
        for f in findings
    ]
    seen = {(e["rule"], e["file"], e["detail"]) for e in entries}
    for e in keep:
        k = (e.get("rule", ""), e.get("file", ""), e.get("detail", ""))
        if k not in seen:
            seen.add(k)
            entries.append(dict(e))
    entries.sort(key=lambda e: (e["rule"], e["file"], e["detail"]))
    with open(path, "w") as fh:
        json.dump(entries, fh, indent=2)
        fh.write("\n")


def diff_findings(
    findings: Sequence[Finding], baseline: Iterable[dict]
) -> Tuple[List[Finding], List[dict]]:
    """Split against the allowlist: ``(new, stale)`` where ``new`` are
    findings with no baseline entry (CI-fatal under ``--check``) and
    ``stale`` are baseline entries whose finding no longer fires (safe;
    reported so the allowlist shrinks)."""
    base_keys = {
        (e.get("rule", ""), e.get("file", ""), e.get("detail", ""))
        for e in baseline
    }
    found_keys = {baseline_key(f) for f in findings}
    new = [f for f in findings if baseline_key(f) not in base_keys]
    stale = [
        e for e in baseline
        if (e.get("rule", ""), e.get("file", ""), e.get("detail", ""))
        not in found_keys
    ]
    return new, stale


def summarize(findings: Sequence[Finding],
              new: Optional[Sequence[Finding]] = None) -> dict:
    """Rule-count summary — the static-health stamp ``bench.py`` embeds
    in its output JSON."""
    by_rule: dict = {}
    by_sev = {s: 0 for s in SEVERITIES}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        by_sev[f.severity] = by_sev.get(f.severity, 0) + 1
    out = {
        "findings": len(findings),
        "by_severity": {k: v for k, v in by_sev.items() if v},
        "by_rule": dict(sorted(by_rule.items())),
    }
    if new is not None:
        out["new"] = len(new)
    return out
