"""Prefetching device-resident data loader.

TPU-native replacement for the reference's forked-Flux ``DataLoader(f,
src; buffersize=5)`` — a background task that keeps a channel of
device-resident batches filled ahead of the training loop
(src/ddp_tasks.jl:277-284; the fork is pinned in the Manifest, see
SURVEY §1).  Here: a thread pool assembles host batches (sampling +
one-hot) and ``jax.device_put``s them with the batch sharding so every
step's input is already laid out across the mesh when the train loop
asks for it — host→HBM transfer overlaps compute exactly as the
reference's prefetch loader overlapped H2D copies.

The loader owns the epoch→cycle accounting the reference does in
``prepare_training`` (``cycles = nrow*epochs ÷ ndev ÷ nsamples``,
src/ddp_tasks.jl:256).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import mesh as mesh_lib
from ..ops import onehot

__all__ = ["PrefetchLoader"]


class PrefetchLoader:
    """Iterate device-sharded ``{"image", "label"}`` batches with background prefetch.

    Parameters
    ----------
    dataset: object with ``nclasses`` and ``batch(rng, n) -> (imgs, labels)``
    mesh: the device mesh; batches are sharded on ``axis``
    batch_size: *global* batch size (reference semantics: per-device batch
        × number of devices; README.md:43's 96/device × N)
    cycles: number of batches to produce; ``None`` derives it from
        ``len(dataset) * epochs // batch_size`` (the reference's
        epoch→cycle conversion, src/ddp_tasks.jl:256)
    buffersize: prefetch depth (reference default 5, src/ddp_tasks.jl:278)
    one_hot: emit one-hot labels (the reference's ``onehotbatch``,
        src/imagenet.jl:47); integer labels otherwise
    transform: optional host-side ``(imgs, labels) -> (imgs, labels)``
    """

    def __init__(
        self,
        dataset,
        mesh: Mesh,
        batch_size: int,
        cycles: Optional[int] = None,
        epochs: int = 1,
        buffersize: int = 5,
        seed: int = 0,
        axis: str = mesh_lib.DATA_AXIS,
        one_hot: bool = True,
        num_threads: int = 2,
        transform: Optional[Callable] = None,
    ):
        n = mesh.shape[axis]
        if batch_size % n:
            raise ValueError(
                f"global batch {batch_size} not divisible by mesh axis '{axis}' size {n}"
            )
        self.dataset = dataset
        self.mesh = mesh
        self.batch_size = batch_size
        self.buffersize = buffersize
        self.one_hot = one_hot
        self.transform = transform
        self.seed = seed
        self.num_threads = max(1, num_threads)
        self.sharding = NamedSharding(mesh, P(axis))
        # Multi-host: each process assembles only its rows of the global
        # batch (the analog of each reference worker sampling its own
        # minibatch, src/sync.jl:135); jax.make_array_from_process_local_data
        # stitches them into one globally-sharded array.
        from ..parallel import multihost

        self._local_batch = multihost.local_batch_size(batch_size)
        if cycles is None:
            cycles = max(1, (len(dataset) * epochs) // batch_size)
        self.cycles = cycles

    # -- host-side batch assembly ------------------------------------
    def _make_batch(self, i: int):
        # Per-batch stream keyed on (seed, process, batch index): batch
        # content is a pure function of the index, so runs with the same
        # seed are bit-reproducible no matter which prefetch thread
        # assembles which batch.  Distinct per process, so hosts sample
        # different rows (the analog of the reference's per-worker
        # sampling, src/sync.jl:135).
        rng = np.random.default_rng((self.seed, jax.process_index(), i))
        imgs, labels = self.dataset.batch(rng, self._local_batch)
        if self.transform is not None:
            imgs, labels = self.transform(imgs, labels)
        return imgs, labels

    def _put(self, imgs, labels):
        from ..parallel.multihost import global_batch_put

        y = np.asarray(labels)
        batch = {
            "image": global_batch_put(np.asarray(imgs), self.sharding),
            "label": global_batch_put(
                np.asarray(onehot(y, self.dataset.nclasses)) if self.one_hot else y,
                self.sharding,
            ),
        }
        return batch

    # -- iteration ----------------------------------------------------
    def __len__(self) -> int:
        return self.cycles

    def __iter__(self) -> Iterator[dict]:
        q: queue.Queue = queue.Queue(maxsize=self.buffersize)
        counter = iter(range(self.cycles))
        lock = threading.Lock()
        stop = threading.Event()

        # Backpressure: workers may run at most ``buffersize`` batches
        # ahead of the consumer (the reorder buffer would otherwise grow
        # unboundedly while the consumer waits on one slow index, holding
        # arbitrarily many device-resident batches in HBM).
        ahead = threading.Semaphore(self.buffersize)

        def worker():
            while not stop.is_set():
                if not ahead.acquire(timeout=0.5):
                    continue
                with lock:
                    i = next(counter, None)
                if i is None:
                    ahead.release()
                    break
                try:
                    imgs, labels = self._make_batch(i)
                    # device_put from a worker thread: transfer overlaps
                    # the consumer's compute, like the reference's
                    # prefetch tasks
                    item = (i, self._put(imgs, labels), None)
                except Exception as e:  # surface to the consumer, don't die silently
                    item = (i, None, e)
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.5)
                        break
                    except queue.Full:
                        continue
                if item[2] is not None:
                    return

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(self.num_threads)
        ]
        for t in threads:
            t.start()

        # Deliver strictly in batch-index order (threads may finish out of
        # order): determinism costs only a small reorder buffer.
        pending: dict = {}
        next_idx = 0
        try:
            while next_idx < self.cycles:
                while next_idx not in pending:
                    i, batch, err = q.get()
                    if err is not None:
                        raise RuntimeError(
                            "prefetch worker failed while assembling a batch"
                        ) from err
                    pending[i] = batch
                yield pending.pop(next_idx)
                next_idx += 1
                ahead.release()
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
