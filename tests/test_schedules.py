"""Deterministic-schedule race-harness tests (ISSUE 20).

Four blocks:

* **plan mechanics** — crossing accounting, explicit preemptions,
  seeded fuzz determinism, reproducer spec round-trip and the
  ``$FDTPU_SCHEDULE_REPRO_DIR`` dump-on-failure path.
* **interposition** — :func:`schedules.instrument` swaps a live
  object's primitives (idempotently), traced wrappers behave like the
  real thing, and the ``cross`` hook is inert with no plan installed.
* **the toy pair** — the acceptance criterion: the seeded race in
  ``fixtures_analysis/toy_racy_scheduler.py`` FAILS under its forced
  schedule, provably does NOT fail without interposition, and the
  fixed variant survives the same hostile schedule.
* **real objects** — Scheduler+FakeLMEngine, FaultPlan, StepWatchdog
  and FlightRecorder run instrumented under preemption/fuzz with their
  output invariants asserted (tokens match the ``fake_tokens`` oracle,
  drain admissions are all-or-nothing, no record is lost).

Everything here runs on :class:`FakeLMEngine` — no compiles, so the
suite belongs in CI's fast job (which exports the repro dir so a
schedule failure uploads its interleaving with the obs artifacts).
"""

import importlib.util
import json
import os
import sys
import threading

import pytest

from fluxdistributed_tpu import faults
from fluxdistributed_tpu.analysis import concurrency, schedules
from fluxdistributed_tpu.obs import FlightRecorder, Registry, StepWatchdog
from fluxdistributed_tpu.serve.scheduler import Draining, Request, Scheduler
from fluxdistributed_tpu.serve.testing import FakeLMEngine, fake_tokens

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures_analysis")

#: the forced interleaving that manifests the toy's lost update: stall
#: the FIRST release of the toy's lock (the read-region exit) long
#: enough for the other barrier-released thread to run to completion
TOY_SITE = "RacyToyScheduler._lock.release"


def _load_toy():
    spec = importlib.util.spec_from_file_location(
        "toy_racy_scheduler",
        os.path.join(FIXTURES, "toy_racy_scheduler.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _no_leaked_schedule():
    # a leaked plan would silently stall every other test's locks
    yield
    schedules.clear_schedule()
    assert schedules.active_schedule() is None


# ------------------------------------------------------------ plan mechanics

def test_plan_counts_crossings_and_fires_explicit_preempt():
    plan = schedules.SchedulePlan()
    plan.preempt_at("a.acquire", at=2, delay=0.0)
    for _ in range(3):
        plan.cross("a.acquire")
    plan.cross("b.release")
    assert plan.crossings("a.acquire") == 3
    assert plan.crossings() == {"a.acquire": 3, "b.release": 1}
    assert plan.fired == 1  # only the at=2 crossing
    log = plan.spec()["log"]
    assert [e["n"] for e in log if e["site"] == "a.acquire"] == [1, 2, 3]
    hits = [e for e in log if e["hit"]]
    assert [(e["site"], e["n"]) for e in hits] == [("a.acquire", 2)]


def test_plan_validates_arguments():
    plan = schedules.SchedulePlan()
    with pytest.raises(ValueError):
        plan.preempt_at("x", at=0)
    with pytest.raises(ValueError):
        plan.preempt_at("x", delay=-1.0)
    with pytest.raises(ValueError):
        plan.fuzz(prob=1.5)


def test_fuzz_is_a_pure_function_of_seed_and_crossing():
    def stall_pattern(seed):
        plan = schedules.SchedulePlan(seed=seed).fuzz(prob=0.5, delay=0.0)
        for i in range(40):
            plan.cross(f"site{i % 4}.held")
        return tuple(e["hit"] for e in plan.spec()["log"])

    a, b = stall_pattern(11), stall_pattern(11)
    assert a == b  # same seed, same crossings -> identical schedule
    assert any(a) and not all(a)  # prob=0.5 actually mixes
    assert stall_pattern(12) != a  # and the seed matters


def test_spec_roundtrip_and_dump(tmp_path):
    plan = schedules.SchedulePlan(seed=7)
    plan.preempt_at("s.release", at=3, times=2, delay=0.01)
    plan.fuzz(prob=0.1, delay=0.002)
    spec = plan.spec()
    assert spec["schema"] == "fdtpu-schedule-repro/v1"

    clone = schedules.SchedulePlan.from_spec(spec)
    # the clone re-injects the same schedule: same seed, same table
    cs = clone.spec()
    assert cs["seed"] == 7
    assert cs["preempt"] == spec["preempt"]
    assert cs["fuzz"] == spec["fuzz"]

    path = plan.dump(str(tmp_path / "sub" / "repro.json"))
    on_disk = json.load(open(path))
    assert on_disk["schema"] == "fdtpu-schedule-repro/v1"
    assert on_disk["preempt"][0]["site"] == "s.release"


def test_run_under_schedule_dumps_reproducer_on_failure(
        tmp_path, monkeypatch):
    monkeypatch.setenv(schedules.REPRO_DIR_ENV, str(tmp_path))
    plan = schedules.SchedulePlan(seed=3).preempt_at("x.held", delay=0.0)

    def boom():
        schedules.cross("x.held")
        raise AssertionError("race caught")

    with pytest.raises(AssertionError):
        schedules.run_under_schedule(plan, boom, repro_name="toy")
    assert schedules.active_schedule() is None  # cleared even on raise
    repro = json.load(open(tmp_path / "toy-seed3.json"))
    assert repro["schema"] == "fdtpu-schedule-repro/v1"
    assert repro["fired"] == 1
    assert repro["crossings"] == {"x.held": 1}


def test_run_under_schedule_success_path_no_dump(tmp_path, monkeypatch):
    monkeypatch.setenv(schedules.REPRO_DIR_ENV, str(tmp_path))
    plan = schedules.SchedulePlan()
    assert schedules.run_under_schedule(plan, lambda: 41 + 1) == 42
    assert os.listdir(tmp_path) == []
    assert schedules.active_schedule() is None


# ------------------------------------------------------------- interposition

def test_cross_is_inert_without_a_plan():
    assert schedules.active_schedule() is None
    schedules.cross("anything.at.all")  # must not raise, must not record


def test_instrument_swaps_primitives_and_is_idempotent():
    class Thing:
        def __init__(self):
            self._lock = threading.Lock()
            self._rlock = threading.RLock()
            self._ev = threading.Event()
            self.data = []  # untouched

    t = schedules.instrument(Thing())
    assert isinstance(t._lock, schedules.TracedLock)
    assert isinstance(t._rlock, schedules.TracedLock)
    assert isinstance(t._ev, schedules.TracedEvent)
    assert t._lock.site == "Thing._lock"
    first = t._lock
    assert schedules.instrument(t)._lock is first  # no double-wrap

    # wrappers behave like the originals
    with t._lock:
        assert t._lock.locked()
    assert not t._lock.locked()
    t._ev.set()
    assert t._ev.is_set() and t._ev.wait(0)
    t._ev.clear()
    assert not t._ev.is_set()


def test_traced_lock_announces_boundaries():
    plan = schedules.install_schedule(schedules.SchedulePlan())
    try:
        lock = schedules.TracedLock(threading.Lock(), "L")
        with lock:
            pass
        assert plan.crossings() == {
            "L.acquire": 1, "L.held": 1, "L.release": 1}
    finally:
        schedules.clear_schedule()


# ------------------------------------------------------------- the toy pair

def test_toy_race_caught_under_forced_schedule():
    # THE acceptance assertion: the seeded race manifests on the first
    # run, every run, under the forced preemption at the read-region
    # exit — and the plan actually injected the stall (fired > 0), so
    # this can never silently decay into a vacuous pass
    toy = _load_toy()
    plan = schedules.SchedulePlan(seed=1).preempt_at(
        TOY_SITE, at=1, delay=0.05)
    assert toy.lost_update_under(plan) is True
    assert plan.fired >= 1


def test_toy_race_missed_without_interposition():
    # the second half of the guard: WITHOUT the harness the window (a
    # few bytecodes) never loses across 20 straight runs.  A long
    # switch interval makes "never" deterministic rather than merely
    # overwhelmingly likely — if this ever fails, the toy no longer
    # needs the harness and both fixtures must be rethought.
    toy = _load_toy()
    old = sys.getswitchinterval()
    sys.setswitchinterval(0.5)
    try:
        for _ in range(20):
            assert toy.hammer(toy.RacyToyScheduler()) == 2
    finally:
        sys.setswitchinterval(old)


def test_toy_fix_survives_the_same_hostile_schedule():
    # the fix (one lock region spanning read+write) under the IDENTICAL
    # schedule: the stall still fires, the update is never lost
    toy = _load_toy()
    plan = schedules.SchedulePlan(seed=1).preempt_at(
        "FixedToyScheduler._lock.release", at=1, delay=0.05)
    assert toy.lost_update_under(plan, cls=toy.FixedToyScheduler) is False
    assert plan.fired >= 1


def test_toy_fix_survives_seeded_fuzz():
    toy = _load_toy()
    for seed in (0, 1, 2):
        plan = schedules.SchedulePlan(seed=seed).fuzz(prob=0.5, delay=0.01)
        assert toy.lost_update_under(plan, cls=toy.FixedToyScheduler) is False


# ------------------------------------------------------------- real objects

def _drive_until(sched, pred, max_steps=100_000):
    for _ in range(max_steps):
        if pred():
            return
        sched.step()
    raise AssertionError("driver did not reach condition")


def test_scheduler_tokens_correct_under_fuzzed_schedule():
    # concurrent submitters + the driver thread stepping, every lock
    # boundary fuzz-stalled: each request's output must still match the
    # fake_tokens oracle and nothing may be lost or double-finished
    eng = FakeLMEngine(max_slots=2)
    sched = schedules.instrument(Scheduler(eng, max_queue=64))
    reqs = [Request(prompt=[i, i + 1], max_new_tokens=4) for i in range(8)]
    plan = schedules.SchedulePlan(seed=5).fuzz(prob=0.3, delay=0.002)

    def run():
        barrier = threading.Barrier(2)

        def submitter(chunk):
            barrier.wait()
            for r in chunk:
                sched.submit(r)

        threads = [threading.Thread(target=submitter, args=(reqs[i::2],))
                   for i in range(2)]
        for t in threads:
            t.start()
        _drive_until(sched, lambda: all(r.done.is_set() for r in reqs))
        for t in threads:
            t.join()

    schedules.run_under_schedule(plan, run)
    assert plan.fired > 0  # the schedule actually perturbed the run
    for r in reqs:
        assert r.generated == fake_tokens(r.prompt, r.max_new_tokens)
    m = sched.metrics()
    assert m["requests_submitted"] == 8
    assert m["requests_finished"] == 8


def test_scheduler_drain_is_all_or_nothing_under_preemption():
    # the begin_drain fix under fire: stall inside the drain-latch lock
    # region while submitters hammer — every submit must either raise
    # Draining or run to completion with correct tokens; no request may
    # be accepted and then dropped
    eng = FakeLMEngine(max_slots=2)
    sched = schedules.instrument(Scheduler(eng, max_queue=64))
    plan = schedules.SchedulePlan(seed=9)
    plan.preempt_at("Scheduler._lock.held", at=3, times=4, delay=0.01)
    accepted, refused = [], []
    acc_lock = threading.Lock()

    def run():
        barrier = threading.Barrier(3)

        def submitter(base):
            barrier.wait()
            for i in range(6):
                r = Request(prompt=[base, i], max_new_tokens=3)
                try:
                    sched.submit(r)
                except Draining:
                    with acc_lock:
                        refused.append(r)
                else:
                    with acc_lock:
                        accepted.append(r)

        threads = [threading.Thread(target=submitter, args=(b,))
                   for b in (10, 20)]
        for t in threads:
            t.start()
        barrier.wait()
        sched.begin_drain()
        for t in threads:
            t.join()
        sched.run_until_idle()

    schedules.run_under_schedule(plan, run)
    assert len(accepted) + len(refused) == 12
    for r in accepted:  # accepted => completed, correctly
        assert r.done.is_set()
        assert r.generated == fake_tokens(r.prompt, r.max_new_tokens)
    for r in refused:  # refused => never entered the machine
        assert not r.done.is_set() and r.generated == []
    with pytest.raises(Draining):
        sched.submit(Request(prompt=[1], max_new_tokens=1))


def test_faultplan_concurrent_arming_under_preemption():
    # the FaultPlan fix under fire: threads arming faults while another
    # fires — stalls injected inside the plan's own lock regions must
    # not lose an armed fault or corrupt the traversal
    plan = schedules.SchedulePlan(seed=4).fuzz(prob=0.4, delay=0.002)
    fp = schedules.instrument(faults.FaultPlan())
    fired = []

    def run():
        barrier = threading.Barrier(3)

        def armer(k):
            barrier.wait()
            for i in range(5):
                fp.fail(f"site-{k}-{i}", message="x")

        def firer():
            barrier.wait()
            for _ in range(40):
                try:
                    fp.fire("site-0-0")
                except faults.FaultInjected:
                    fired.append(1)

        threads = [threading.Thread(target=armer, args=(k,))
                   for k in (0, 1)] + [threading.Thread(target=firer)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    schedules.run_under_schedule(plan, run)
    # every armed fault is present: each never-fired single-shot site
    # still raises exactly once — a lost append would pass silently here
    for k in (0, 1):
        for i in range(5):
            if (k, i) == (0, 0) and fired:
                continue
            with pytest.raises(faults.FaultInjected):
                fp.fire(f"site-{k}-{i}")


def test_faultplan_static_pin():
    # the static half of the regression pair: faults.py scans FDT3xx
    # clean (the unlocked appends this layer originally caught stay
    # fixed)
    findings = concurrency.run_concurrency_checks(
        ["fluxdistributed_tpu/faults.py"])
    assert findings == [], findings


def test_watchdog_concurrent_beats_under_fuzz():
    reg = Registry()  # private: no cross-test gauge collisions
    wd = schedules.instrument(StepWatchdog(registry=reg))
    plan = schedules.SchedulePlan(seed=6).fuzz(prob=0.3, delay=0.001)

    def run():
        barrier = threading.Barrier(2)

        def beater():
            barrier.wait()
            for _ in range(50):
                wd.beat()

        threads = [threading.Thread(target=beater) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wd.poll()

    schedules.run_under_schedule(plan, run)
    assert plan.fired > 0
    # 100 beats from 2 threads, none lost to a stalled interleaving,
    # and no spurious stall episode from the injected delays
    assert wd._beats == 100
    assert reg.value("fdtpu_watchdog_stalls_total") == 0.0


def test_flight_recorder_loses_nothing_under_fuzz(tmp_path):
    path = str(tmp_path / "flight.jsonl")
    fr = schedules.instrument(FlightRecorder(path, ring=512, flush_every=4))
    plan = schedules.SchedulePlan(seed=8).fuzz(prob=0.3, delay=0.001)

    def run():
        barrier = threading.Barrier(3)

        def writer(k):
            barrier.wait()
            for i in range(40):
                fr.record(src=k, i=i)

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    schedules.run_under_schedule(plan, run)
    assert plan.fired > 0
    assert fr.recorded == 120
    fr.dump("ok")
    on_disk = [json.loads(l) for l in open(path) if l.strip()]
    recs = [r for r in on_disk if r.get("kind") == "record"]
    assert len(recs) == 120  # crash-durable: every record flushed
    # per-writer streams arrive intact and in program order
    for k in range(3):
        assert [r["i"] for r in recs if r["src"] == k] == list(range(40))
