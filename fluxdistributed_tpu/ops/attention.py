"""Attention ops — net-new TPU scope beyond the reference.

The reference is vision-CNN-only (no attention anywhere; SURVEY §5
"long-context: absent"), but this framework treats long-context as
first-class: these ops are the single source of attention semantics for

* the ViT model family (``models/vit.py`` — the ViT-L/16 BASELINE config),
* the Pallas flash-attention TPU kernel and the ring-attention context
  parallelism layer, both of which reuse the online-softmax block update
  defined here.

All functions take ``q, k, v`` shaped ``[batch, seq, heads, head_dim]``
(BTHD — the layout XLA prefers for TPU attention: the matmuls contract
over head_dim/seq and batch×heads map onto MXU batching).  Softmax
statistics are always accumulated in float32 regardless of input dtype.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "dot_product_attention",
    "blockwise_attention",
    "attention_core",
    "AttnCarry",
    "attn_block_update",
    "attn_finalize",
    "online_softmax_update",
]

NEG_INF = -1e30


def attention_core(kind: str, block: int = 128, window: Optional[int] = None,
                   sinks: int = 0):
    """Resolve an ``--attn``-style core name to a causal ``attn_fn``.

    The single source of the dense/blockwise/flash wiring shared by
    ``bin/driver.py`` and ``benchmarks/lm_bench.py`` (one flag, one
    meaning).  ``"dense"`` → None when no window is set (the model's
    built-in core), else a windowed dense core.  ``window`` restricts
    each query to its ``window`` newest keys (sliding-window attention;
    only the flash core skips out-of-band blocks' FLOPs).
    """
    from functools import partial

    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if sinks and window is None:
        raise ValueError("sinks only make sense with a window")
    if kind == "dense":
        if window is None:
            return None
        return partial(dot_product_attention, causal=True, window=window,
                       sinks=sinks)
    if block <= 0:
        raise ValueError(f"attention block size must be > 0, got {block}")
    if kind == "blockwise":
        return partial(blockwise_attention, block_size=block, causal=True,
                       window=window, sinks=sinks)
    if kind == "flash":
        from .pallas_attention import flash_attention

        def _flash(q, k, v):
            # the training kernel amortizes its [block_q, block_k] tiles
            # over many query rows; a single-query (decode-shaped) call
            # would silently run it at its worst shape — the decode
            # kernels exist for exactly that workload
            if q.shape[1] == 1 and k.shape[1] > 1:
                raise ValueError(
                    "attention_core(kind='flash') is the training/prefill "
                    "kernel; single-query decode-shaped inputs (Tq=1 vs a "
                    f"Tk={k.shape[1]} cache) belong to the flash-decode "
                    "kernels (ops.pallas_decode.flash_decode[_paged]) — "
                    "the serve engine wires them via attention_impl="
                    "'pallas'")
            return flash_attention(q, k, v, True, block, block,
                                   window, sinks)

        return _flash
    raise ValueError(f"unknown attention core {kind!r}")


def _scale(q):
    return q / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32)).astype(q.dtype)


def _expand_kv(q, k, v):
    """Broadcast grouped KV heads up to the query head count (GQA).

    The XLA cores take the simple route — materialize the repeat and let
    the compiler fuse it; the Pallas kernel instead maps query-head
    programs onto shared KV blocks so grouped KV is never repeated in
    HBM (ops/pallas_attention.py).
    """
    h, hkv = q.shape[2], k.shape[2]
    if h == hkv:
        return k, v
    if h % hkv:
        raise ValueError(
            f"num query heads ({h}) must be a multiple of num KV heads ({hkv})")
    rep = h // hkv
    return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
    window: Optional[int] = None,
    sinks: int = 0,
) -> jax.Array:
    """Reference softmax attention, one XLA fusion.

    ``q``: [B, Tq, H, D]; ``k``/``v``: [B, Tk, H, D] → [B, Tq, H, D].
    ``mask``: optional [B?, H?, Tq, Tk] additive-compatible boolean mask
    (True = attend).  f32 softmax, output in q.dtype.

    Rows with NO attendable position (all-False mask row, or causal rows
    before the first key when Tq > Tk) return exactly 0 — the same
    convention as every other attention implementation in this package.
    Grouped-query KV ([B, Tk, Hkv, D] with Hkv dividing H) is accepted
    and broadcast to the query head count.
    """
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if sinks and window is None:
        raise ValueError("sinks only make sense with a window")
    k, v = _expand_kv(q, k, v)
    q = _scale(q)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    tq, tk = s.shape[-2], s.shape[-1]
    allow = None
    if causal:
        # Align ends: allows Tq != Tk (e.g. decoding with a KV cache).
        idx_q = jnp.arange(tq)[:, None] + (tk - tq)
        causal_ok = jnp.arange(tk)[None, :] <= idx_q
        allow = causal_ok
        if window is not None:
            # sliding window: each query sees its `window` newest keys,
            # plus the first `sinks` positions (StreamingLLM sinks)
            in_band = jnp.arange(tk)[None, :] >= idx_q - (window - 1)
            if sinks:
                in_band |= jnp.arange(tk)[None, :] < sinks
            allow &= in_band
        allow = allow[None, None]
    if mask is not None:
        allow = mask if allow is None else allow & mask
    if allow is not None:
        s = jnp.where(allow, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if allow is not None:
        # Softmax over an all-NEG_INF row is uniform; zero it so fully-
        # masked rows output 0, matching blockwise/flash.
        p = jnp.where(allow, p, 0.0)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


class AttnCarry(NamedTuple):
    """Online-softmax accumulator state for blockwise/ring attention.

    ``o``   [B, Tq, H, D] float32 — un-normalized output accumulator
    ``m``   [B, H, Tq]    float32 — running row max of scores
    ``l``   [B, H, Tq]    float32 — running sum of exp(scores - m)
    """

    o: jax.Array
    m: jax.Array
    l: jax.Array


def online_softmax_update(s, m_prev, l_prev, mask=None):
    """The online-softmax statistics update — THE shared numerics.

    Used by ``attn_block_update`` (XLA blockwise + ring attention) and by
    the Pallas flash kernel, so the masking/accumulation semantics cannot
    drift between implementations.

    ``s``: [..., q, k] f32 scores (pre-scaled).  ``m_prev``/``l_prev``:
    [..., q].  ``mask``: optional [..., q, k] boolean, True = attend.
    Returns ``(p, corr, m_new, l_new)`` where ``p`` is the un-normalized
    block softmax (zeroed at masked positions — rows masked everywhere
    keep ``l == 0`` and finalize to 0) and ``corr`` rescales the caller's
    output accumulator.
    """
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m_prev, s.max(axis=-1))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        # For a row masked in EVERY position so far, m_new is still
        # NEG_INF and exp(s - m_new) = exp(0) = 1 — zero explicitly.
        p = jnp.where(mask, p, 0.0)
    l_new = l_prev * corr + p.sum(axis=-1)
    return p, corr, m_new, l_new


def attn_init(q: jax.Array) -> AttnCarry:
    b, tq, h, d = q.shape
    return AttnCarry(
        o=jnp.zeros((b, tq, h, d), jnp.float32),
        m=jnp.full((b, h, tq), NEG_INF, jnp.float32),
        l=jnp.zeros((b, h, tq), jnp.float32),
    )


def attn_block_update(
    carry: AttnCarry,
    q_scaled: jax.Array,
    k_blk: jax.Array,
    v_blk: jax.Array,
    *,
    mask: Optional[jax.Array] = None,
) -> AttnCarry:
    """Fold one KV block into the online-softmax accumulator.

    The single numerical building block shared by ``blockwise_attention``
    (local loop over KV blocks) and ring attention (loop over KV blocks
    arriving over ICI via ``ppermute``).  ``q_scaled`` must already be
    divided by sqrt(head_dim) — scaling is the caller's job so it happens
    once, not once per block inside a scan.  ``mask``: [Tq, Tk_blk]
    boolean, True = attend (causal masking with global positions, and
    padding introduced by non-divisible block sizes).
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q_scaled, k_blk, preferred_element_type=jnp.float32
    )
    p, corr, m_new, l_new = online_softmax_update(
        s, carry.m, carry.l, mask=None if mask is None else mask[None, None]
    )
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
    o_new = carry.o * corr.transpose(0, 2, 1)[..., None] + pv
    return AttnCarry(o=o_new, m=m_new, l=l_new)


def attn_finalize(carry: AttnCarry, dtype) -> jax.Array:
    """Normalize the accumulator into the final attention output."""
    l = jnp.maximum(carry.l, 1e-30)  # fully-masked rows → 0 output, not NaN
    return (carry.o / l.transpose(0, 2, 1)[..., None]).astype(dtype)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    block_size: int = 512,
    causal: bool = False,
    window: Optional[int] = None,
    sinks: int = 0,
) -> jax.Array:
    """Flash-style attention via ``lax.scan`` over KV blocks.

    Memory-bounded in seq length (never materializes [Tq, Tk] for the
    full sequence) with identical numerics to ``dot_product_attention``.
    This is the XLA fallback for the Pallas kernel and the single-device
    analog of ring attention (one ring hop == one scan iteration).
    Grouped-query KV is accepted (broadcast to the query head count).
    ``window`` (causal only) masks keys older than the query's
    ``window`` newest — the scan still visits every block (use the
    Pallas kernel for the FLOPs saving).
    """
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if sinks and window is None:
        raise ValueError("sinks only make sense with a window")
    k, v = _expand_kv(q, k, v)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    block_size = min(block_size, tk)
    pad = -tk % block_size  # pad (masked) rather than fall back to one block
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    nblocks = (tk + pad) // block_size
    kb = k.reshape(b, nblocks, block_size, h, d).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(b, nblocks, block_size, h, d).transpose(1, 0, 2, 3, 4)

    q_scaled = _scale(q)
    q_pos = jnp.arange(tq) + (tk - tq)

    def body(carry, xs):
        blk_idx, k_blk, v_blk = xs
        k_pos = blk_idx * block_size + jnp.arange(block_size)
        mask = k_pos[None, :] < tk
        if causal:
            mask &= k_pos[None, :] <= q_pos[:, None]
            if window is not None:
                in_band = k_pos[None, :] >= q_pos[:, None] - (window - 1)
                if sinks:
                    in_band |= k_pos[None, :] < sinks
                mask &= in_band
        elif not pad:
            mask = None
        return attn_block_update(carry, q_scaled, k_blk, v_blk, mask=mask), None

    carry, _ = jax.lax.scan(body, attn_init(q), (jnp.arange(nblocks), kb, vb))
    return attn_finalize(carry, q.dtype)
