"""Benchmark harness: ResNet-50/ImageNet train-step throughput.

The reference publishes NO benchmark numbers (SURVEY §6, BASELINE.md) —
its only timing hook is dead code.  This harness therefore defines the
baseline: steady-state images/sec/chip for the full compiled DP training
step (forward + backward + grad all-reduce + optimizer update, bf16
compute) on synthetic 224x224 data, the reference's headline workload
(ResNet-50/ImageNet, README.md:27,43).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

``vs_baseline`` compares against BASELINE_IMAGES_PER_SEC_PER_CHIP below —
the first recorded number for this framework (the reference has none to
compare against).  Update it when the bench improves materially.
"""

from __future__ import annotations

import json
import sys
import time
import traceback

# First recorded value on the one available chip (TPU v5e, global batch
# 256, bf16): ~2270 img/s/chip, reproduced across three bench runs
# (2026-07-29), measured under the then-current f32 input feed.  Batch
# 128-512 measured flat within ~±5%; vs_baseline is against the repeated
# 256/chip measurement.  The bench now feeds bf16, so vs_baseline
# includes that protocol change until the constant is re-recorded on
# hardware under the new feed.
BASELINE_IMAGES_PER_SEC_PER_CHIP = 2270.0


def time_compiled_step(step, state, b, target_seconds: float = 2.0,
                       on_compiled=None):
    """Shared measurement protocol: compile + 3-step warmup (the first
    post-compile steps can still hit allocator warm-up and skew short
    timings), then an adaptive timed loop covering ``target_seconds``.
    Returns ``(seconds_per_step, iters)``.  benchmarks/step_sweep.py uses
    this same helper so sweep rows stay comparable to the headline.
    ``on_compiled`` fires once the first step has landed (compilation
    over) — the bench's phase marker for timeout forensics."""
    import time as _time

    import jax

    state, m = step(state, b)
    jax.block_until_ready(m["loss"])
    if on_compiled is not None:
        on_compiled()
    t0 = _time.perf_counter()
    for _ in range(3):
        state, m = step(state, b)
    jax.block_until_ready(m["loss"])
    warm = (_time.perf_counter() - t0) / 3

    iters = max(5, int(target_seconds / max(warm, 1e-3)))
    t0 = _time.perf_counter()
    for _ in range(iters):
        state, m = step(state, b)
    jax.block_until_ready(m["loss"])
    return (_time.perf_counter() - t0) / iters, iters


def fuse_steps(step, k: int, donate: bool = True):
    """Wrap a compiled ``step(state, batch) -> (state, metrics)`` into ONE
    program running ``k`` optimizer steps on the same device-resident
    batch.  Isolates host-side dispatch cost: when the runtime sits
    behind a network tunnel (axon), each un-fused step pays a dispatch
    round-trip; ``k`` fused steps pay one.  Semantics differ from real
    training only in reusing the batch — throughput is identical."""
    import jax

    def multi(state, b):
        def body(_, carry):
            st, _m = carry
            return step(st, b)

        # one step seeds the (state, metrics) carry; k-1 more in the loop
        return jax.lax.fori_loop(0, k - 1, body, step(state, b))

    return jax.jit(multi, donate_argnums=(0,) if donate else ())


def build_step(
    batch: int,
    size: int = 224,
    donate: bool = True,
    accum_steps: int = 1,
    norm_dtype=None,
    input_f32: bool = False,
    remat: bool = False,
    fuse: int = 1,
    s2d: bool = False,
    zero1: bool = False,
    layout: str | None = None,
):
    """Build the headline measurement target: ResNet-50, DP mesh over all
    chips, compiled train step, device-resident batch.

    Returns ``(step, state, batch_dict)``.  This is THE protocol —
    benchmarks/step_sweep.py varies its knobs through here so sweep rows
    stay comparable to the headline number.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    import fluxdistributed_tpu as fd
    from fluxdistributed_tpu import optim, sharding
    from fluxdistributed_tpu.models import resnet50
    from fluxdistributed_tpu.parallel import TrainState, make_train_step
    from fluxdistributed_tpu.parallel.dp import flax_loss_fn

    lay = None
    if layout:
        # rule-derived dp x fsdp x tp placement (parallel/layout.py):
        # the mesh and the state shardings come from the preset's rule
        # table + fsdp overlay — sweep rows measure the SAME step math
        # under a different placement
        from fluxdistributed_tpu.parallel import layout as layout_lib

        if zero1:
            raise ValueError("layout= and zero1= are exclusive (a "
                             "layout's fsdp axis shards the optimizer)")
        lay = layout_lib.resolve_layout(layout)
        mesh = lay.build_mesh()
    else:
        mesh = fd.data_mesh()
    model = resnet50(
        num_classes=1000, norm_dtype=norm_dtype, remat=remat,
        space_to_depth=s2d,
    )
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (batch, size, size, 3)).astype(np.float32)
    if s2d:
        # host-side re-layout, like a real input pipeline would feed it
        from fluxdistributed_tpu.models.resnet import space_to_depth

        x = np.ascontiguousarray(space_to_depth(x))
    y = rng.integers(0, 1000, batch)

    variables = model.init(jax.random.PRNGKey(0), x[:1], train=True)
    params = variables["params"]
    mstate = {k: v for k, v in variables.items() if k != "params"}

    loss_fn = flax_loss_fn(model, fd.logitcrossentropy)
    opt = optim.momentum(0.1, 0.9)
    if zero1:
        # ZeRO-1 weight-update sharding: same step math, optimizer state
        # + update compute sharded 1/N over the data axis
        from fluxdistributed_tpu.parallel import zero1 as zero1_lib

        state, z_sh = zero1_lib.zero1_state(
            params, opt, mesh, model_state=sharding.replicate(mstate, mesh)
        )
        step = zero1_lib.make_train_step_zero1(
            loss_fn, opt, mesh, z_sh, donate=donate, accum_steps=accum_steps
        )
    elif lay is not None:
        from fluxdistributed_tpu.parallel import layout as layout_lib

        state = TrainState.create(params, opt, model_state=mstate)
        spec_state = layout_lib.state_specs_for(
            model, state, lay, mesh)
        sh = sharding.make_shardings(spec_state, mesh)
        state = jax.tree.map(
            lambda v, s: jax.device_put(sharding.unaliased(v), s),
            state, sh)
        step = make_train_step(
            loss_fn, opt, mesh, axis=lay.batch_axes, donate=donate,
            accum_steps=accum_steps, state_shardings=sh)
    else:
        step = make_train_step(loss_fn, opt, mesh, donate=donate, accum_steps=accum_steps)
        state = TrainState.create(
            sharding.replicate(params, mesh), opt, model_state=sharding.replicate(mstate, mesh)
        )
    # feed bf16 by default: the model casts to bf16 at its input anyway,
    # so an f32 feed only adds a 2x-wider HBM read + an in-graph convert
    xb = x if input_f32 else x.astype(jnp.bfloat16)
    b = sharding.shard_batch(
        {"image": xb, "label": np.asarray(fd.onehot(y, 1000))}, mesh,
        axis=(lay.batch_axes if lay is not None else "data"),
    )
    if fuse > 1:
        step = fuse_steps(step, fuse, donate=donate)
    return step, state, b


# bf16 peak TFLOP/s per chip, for the MFU denominator.  Keys are
# substring-matched against jax's device_kind (e.g. "TPU v5 lite").
_PEAK_BF16_TFLOPS = {
    "v5 lite": 197.0, "v5e": 197.0, "v5p": 459.0,
    "v4": 275.0, "v6": 918.0,
}


def step_flops(step, state, b) -> float:
    """Total FLOPs of one step from XLA's HLO cost analysis on the
    LOWERED (pre-compile) program — no second backend compile, which
    matters when compiles go through a remote tunnel.  0.0 when the
    analysis is unavailable."""
    try:
        ca = step.lower(state, b).cost_analysis()
        d = ca[0] if isinstance(ca, (list, tuple)) else ca
        return float(d.get("flops", 0.0)) if d else 0.0
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return 0.0


def mfu_pct(flops: float, dt: float, nchips: int):
    """Model-FLOPs-utilization of a measured step: achieved FLOP/s per
    chip over the chip's bf16 peak.  None when the device peak is
    unknown (CPU) or XLA reports no FLOP count."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    peak = next(
        (v for k, v in _PEAK_BF16_TFLOPS.items() if k in kind), None
    )
    if not peak or not flops:
        return None
    return round(flops / dt / nchips / (peak * 1e12) * 100, 2)


def guard_stamp():
    """The robustness-counter stamp for the bench JSON: every
    ``fdtpu_guard_* / fdtpu_fault_* / fdtpu_watchdog_*`` series (plus
    the OOM-skip counter) snapshotted from the process registry.  A
    dead hardware round's artifact then records WHY it died — faults
    injected/retried/given up, stalls and escalations, anomalies
    quarantined — instead of a bare ``value: 0``.  Like
    :func:`lint_stamp`, it never raises and rides success and error
    JSON alike."""
    try:
        from fluxdistributed_tpu.obs import get_registry

        snap = get_registry().snapshot()
        keep = ("fdtpu_guard_", "fdtpu_fault_", "fdtpu_watchdog_",
                "fdtpu_train_oom_skipped_total")
        out = {k: v for k, v in snap.items()
               if k.startswith(keep) and v}
        return out or {"clean": True}
    except Exception as e:  # noqa: BLE001 — stamp is best-effort
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def memory_stamp(state=None):
    """The HBM stamp for the bench JSON: live per-device memory truth
    (``device.memory_stats()`` through obs.memstats — bytes in use,
    PEAK since process start, limit and the min headroom ratio) plus,
    when the bench state is at hand, its exact static bytes (params /
    optimizer state off the leaf shapes).  On CPU it reads
    ``{"available": false}`` — unavailable, never fake zeros.  Like the
    lint/guard stamps it never raises and rides success AND error JSON
    (relayed through the child status file), so a dead hardware round
    records the memory state at death — the difference between "the
    grant expired" and "we were at 2% headroom when it OOMed"."""
    try:
        from fluxdistributed_tpu.obs import memstats

        out = memstats.hbm_summary()
        if state is not None:
            out["static"] = memstats.state_bytes(state)
        return out
    except Exception as e:  # noqa: BLE001 — stamp is best-effort
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def lint_stamp():
    """The static-health stamp for the bench JSON: the AST-layer
    (FDT1xx) + concurrency-layer (FDT3xx) rule-count summary, a
    per-layer ``"layers"`` breakdown, and the new-vs-baseline count
    from the fdtpu-lint suite (seconds of pure host-side parsing, no
    jax tracing — safe inside the bounded measurement subprocess).  A
    hardware round whose artifact says ``"new": 0`` provably ran code
    the analyzer had no fresh complaints about — including no unlocked
    shared-state writes or lock-order cycles; a non-zero count flags
    the round as statically suspect before anyone re-burns a grant
    window reproducing it.  Never raises — forensics must not kill the
    bench."""
    try:
        from fluxdistributed_tpu import analysis

        return analysis.lint_verdict()
    except Exception as e:  # noqa: BLE001 — stamp is best-effort
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def pp_plan_stamp():
    """The pipeline-planner paired-row stamp for the bench JSON: the
    profile-guided planner (parallel/pp_plan.py) run on THIS box's
    static costs for a production-shaped LM (lm_small geometry, 32k
    vocab) — uniform vs planned stage boundaries with the modeled
    bubble of each.  Staging only, nothing compiles, and like the
    lint/guard stamps it never raises: every round's artifact records
    whether (and by how much) planner placement beats uniform splits
    here, next to the measured rows hw_session's pp_bubble stage
    produces."""
    try:
        from fluxdistributed_tpu.models.transformer_lm import lm_small
        from fluxdistributed_tpu.parallel.pp_plan import plan_from_model

        S, M = 4, 16
        model = lm_small(dropout=0.0)
        plan = plan_from_model(model, S, M, batch_size=8, seqlen=1024)
        return {
            "S": S, "M": M, "depth": int(model.depth),
            "boundaries_planned": list(plan.boundaries),
            "counts_planned": list(plan.counts),
            "modeled_bubble_planned": round(plan.modeled_bubble, 4),
            "modeled_bubble_uniform": round(plan.uniform_bubble, 4),
        }
    except Exception as e:  # noqa: BLE001 — stamp is best-effort
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def layout_pick_stamp():
    """The auto-layout picker's verdict for the bench JSON
    (parallel/layout.py): chosen dp x fsdp x tp layout for the bench-
    shaped LM on THIS topology, with each candidate's peak bytes /
    headroom and collective-ledger figures.  Budget comes from the live
    per-device ``bytes_limit`` when the backend reports one (real
    chips); without it (CPU) the ranking is by collective bytes alone,
    honestly flagged.  Prices candidates by ABSTRACT compiles (no
    parameter buffer allocates) — bounded cost, and like the lint/
    guard/memory stamps it never raises: dead rounds record what the
    picker would have chosen next to why the round died."""
    try:
        import jax
        import numpy as np

        from fluxdistributed_tpu import optim
        from fluxdistributed_tpu.models.transformer_lm import lm_tiny
        from fluxdistributed_tpu.parallel import layout as layout_lib

        model = lm_tiny(dropout=0.0)
        batch = {"tokens": jax.ShapeDtypeStruct((16, 128), np.int32)}
        rep = layout_lib.pick(model, batch, optim.adam(1e-3))
        rows = [{k: r.get(k) for k in (
                    "layout", "peak_bytes", "headroom_bytes", "fits",
                    "comms_bytes", "comms_bytes_per_axis", "invalid")
                 if r.get(k) is not None}
                for r in rep.rows]
        return {"chosen": rep.chosen.name if rep.chosen else None,
                "chosen_sizes": rep.chosen.sizes if rep.chosen else None,
                "budget_bytes": rep.budget_bytes,
                "reason": rep.reason,
                "rows": rows}
    except Exception as e:  # noqa: BLE001 — stamp is best-effort
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _bounded_stamp(fn, seconds: float, site: str):
    """Run one forensic stamp under a wall bound (the with_retries
    tries=1 timeout shape): EVERY error-path stamp must carry this —
    a wedged backend can block any of them in C (registry callbacks
    and memory_stats() both reach into the runtime), and an error JSON
    that hangs behind its own forensics never reaches the watcher.
    A timeout records itself instead of wedging the report."""
    try:
        from fluxdistributed_tpu import faults

        return faults.with_retries(fn, tries=1, timeout=seconds,
                                   site=site)
    except Exception as e:  # noqa: BLE001 — stamp is best-effort
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def layout_pick_stamp_bounded(seconds: float = 120.0):
    """The picker stamp under a wall bound — error-path JSON must not
    hang behind a wedged backend's compile attempt (the picker prices
    candidates by compiling; a dead tunneled chip can block that in C).
    A timeout records itself instead of wedging the error report."""
    return _bounded_stamp(layout_pick_stamp, seconds,
                          "bench.layout_stamp")


def guard_stamp_bounded(seconds: float = 30.0):
    """:func:`guard_stamp` under a wall bound for error paths: the
    registry snapshot walks scrape-time callback gauges, and a callback
    that reads a wedged runtime would hang the error JSON."""
    return _bounded_stamp(guard_stamp, seconds, "bench.guard_stamp")


def memory_stamp_bounded(seconds: float = 30.0):
    """:func:`memory_stamp` under a wall bound for error paths:
    ``device.memory_stats()`` is a runtime call — exactly the kind of
    thing a dead tunneled chip blocks forever."""
    return _bounded_stamp(memory_stamp, seconds, "bench.memory_stamp")


def lint_stamp_bounded(seconds: float = 60.0):
    """:func:`lint_stamp` under a wall bound for error paths: pure
    host-side AST work in theory (both layers — the concurrency pass
    re-parses the tree too), but it globs + parses the whole tree — a
    hung NFS mount must not wedge the error report either."""
    return _bounded_stamp(lint_stamp, seconds, "bench.lint_stamp")


def default_runs_ledger():
    """Resolve the cross-run ledger path for bench runs:
    ``FDTPU_RUNS_LEDGER`` when set (empty string disables), else
    ``benchmarks/hw/runs.jsonl`` next to this file — the history
    ``bin/trends.py`` renders trends from and gates regressions
    against."""
    import os

    env = os.environ.get("FDTPU_RUNS_LEDGER")
    if env is not None:
        return env or None
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "hw", "runs.jsonl")


def append_run_record(out, kind="bench", fingerprint=None):
    """Mirror one bench JSON (success AND error alike) into the
    cross-run ledger (obs.runs).  Best-effort by contract: the ledger
    append must never change what the bench prints or returns."""
    try:
        path = default_runs_ledger()
        if not path:
            return
        from fluxdistributed_tpu.obs import runs as runs_lib

        metrics = {}
        if out.get("value"):
            metrics["throughput"] = out["value"]
        if out.get("mfu_pct") is not None:
            metrics["mfu_pct"] = out["mfu_pct"]
        if out.get("compile_seconds"):
            metrics["compile_seconds"] = out["compile_seconds"]
        stamps = {k: out[k] for k in
                  ("lint", "guard", "memory", "layout_pick", "pp_plan")
                  if k in out}
        extra = {k: out[k] for k in
                 ("probe_attempts", "probe_last", "unit", "warmed",
                  "aot_loaded", "cache_hits", "cache_misses")
                 if k in out}
        runs_lib.append_run(path, runs_lib.run_record(
            kind,
            fingerprint=fingerprint,
            phase=out.get("phase"),
            retryable=out.get("retryable"),
            error=out.get("error"),
            metrics=metrics,
            stamps=stamps or None,
            **extra))
    except Exception:  # noqa: BLE001 — the ledger is forensics
        pass


def default_cache_dir():
    """Resolve the persistent-compile-cache root for bench runs:
    ``FDTPU_COMPILE_CACHE_DIR`` when set (empty string disables), else
    ``benchmarks/hw/xla_cache`` next to this file — the same directory
    the availability watcher exports, so grant-window attempt N+1 reads
    attempt N's compiles off disk instead of redoing them inside the
    window."""
    import os

    env = os.environ.get("FDTPU_COMPILE_CACHE_DIR")
    if env is not None:
        return env or None
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "hw", "xla_cache")


def default_aot_dir():
    """Serialized-executable directory for the resumable bench
    (``FDTPU_AOT_DIR`` overrides): where attempt N leaves the compiled
    train-step so attempt N+1 skips tracing+lowering+compilation
    entirely."""
    import os

    env = os.environ.get("FDTPU_AOT_DIR")
    if env is not None:
        return env or None
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "hw", "aot")


def _unavailable_sigs():
    """The canonical backend-unavailable signature list lives in
    ``fluxdistributed_tpu.faults`` (one source, no drift); a frozen
    fallback keeps the error-JSON path alive even when the package
    itself cannot import (that is precisely an error path)."""
    try:
        from fluxdistributed_tpu.faults import UNAVAILABLE_SIGNATURES

        return UNAVAILABLE_SIGNATURES
    except Exception:  # noqa: BLE001 — classification must never crash
        return ("UNAVAILABLE", "DEADLINE_EXCEEDED", "failed to connect",
                "Connection reset", "Connection refused", "Socket closed",
                "response body closed", "remote_compile",
                "No visible device", "Unable to initialize backend",
                "timed out", "per-attempt bound")


def retryable_error(phase: str, err: str) -> bool:
    """Phase-aware transient/permanent classification for bench error
    JSON: the availability watcher backs off and retries ONLY when this
    says True — a real code failure must stop the hammering and page a
    human instead of burning grant windows on it.

    * ``backend_init`` — always retryable: death while acquiring the
      backend IS the unavailability being waited out;
    * everything else (``build`` / ``compile`` / ``measure``) —
      retryable only when the error carries a backend-unavailable
      signature (tunnel drop, runtime eviction, timeout: the
      compile-window expiry the resumable protocol resumes from shows
      up as a timeout signature).  A deterministic Python/XLA error in
      any phase — including compile — is permanent: retrying a broken
      build burns grant windows without ever succeeding.
    """
    if phase == "backend_init":
        return True
    err = err or ""
    return any(sig in err for sig in _unavailable_sigs())


def _read_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None


def _write_json_atomic(path, obj):
    import os

    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def _measure_compiled(compiled, state, b, steps: int):
    """Steady-state seconds/step of an AOT executable: one landing call
    (allocator warm-up), then ``steps`` timed calls.  The executable
    donates its state input (build_step default), so the returned state
    is carried exactly like the jit measurement path."""
    import time as _time

    import jax

    state, m = compiled(state, b)
    jax.block_until_ready(m["loss"])
    t0 = _time.perf_counter()
    for _ in range(steps):
        state, m = compiled(state, b)
    jax.block_until_ready(m["loss"])
    return (_time.perf_counter() - t0) / steps


def resumable_main(argv=None) -> int:
    """``bench.py --resumable``: the time-boxed, attempt-chained bench.

    Every previous hardware round died because ONE attempt had to
    survive backend acquisition AND full compilation AND measurement
    inside one grant window.  This mode is a state machine persisted in
    an attempt ledger (JSON, atomic writes): attempt N acquires the
    backend with retries, warms the persistent compile cache, and
    serializes the compiled step as an AOT executable — that progress
    is durable.  Attempt N+1 (any later process) loads the executable
    (no tracing, no lowering, no compiling) and measures a HANDFUL of
    steps — emitting a partial-but-real number with ``attempts`` /
    ``interrupted_at`` provenance instead of a perfect number never.
    When one attempt has budget for both halves it finishes in one go.

    Always prints exactly one JSON line and exits 0; errors carry a
    phase-aware ``retryable`` flag so the watcher backs off only on
    availability problems (``benchmarks/hw_watch.sh``).
    """
    import argparse
    import os

    ap = argparse.ArgumentParser(prog="bench.py --resumable")
    ap.add_argument("--ledger", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks", "hw", "resumable.json"))
    ap.add_argument("--budget", type=float,
                    default=float(os.environ.get("BENCH_BUDGET", 360.0)),
                    help="wall-second box for THIS attempt; progress "
                         "past the warm phase persists either way")
    ap.add_argument("--steps", type=int, default=8,
                    help="measured steps (a handful: partial-but-real)")
    ap.add_argument("--measure-margin", type=float, default=45.0,
                    help="minimum budget left to attempt the measure "
                         "phase in the same attempt that warmed")
    args = ap.parse_args(argv)

    deadline = time.monotonic() + args.budget
    ledger = _read_json(args.ledger) or {
        "version": 1, "state": "cold", "attempts": []}
    attempt = {"n": len(ledger["attempts"]) + 1, "phase": "backend_init",
               "budget": args.budget}
    ledger["attempts"].append(attempt)
    status_path = os.environ.get("BENCH_STATUS_FILE")

    def phase(p):
        attempt["phase"] = p
        _write_status(status_path, p)
        _write_json_atomic(args.ledger, ledger)

    def provenance():
        failed = [a.get("phase") for a in ledger["attempts"]
                  if "error" in a]
        return {
            "attempts": len(ledger["attempts"]),
            "interrupted_at": failed[-1] if failed else None,
            "state": ledger["state"],
            "ledger": args.ledger,
        }

    try:
        from fluxdistributed_tpu import compilation, faults
        from fluxdistributed_tpu.obs import jaxmon

        jaxmon.install()
        phase("backend_init")
        # bounded, retried, classified: a non-granting chip costs
        # minutes here, never a wedged process
        faults.acquire_backend(
            tries=3, timeout=min(120.0, max(30.0, args.budget / 3)),
            backoff=10.0, budget=max(30.0, deadline - time.monotonic()))

        import jax

        platform = jax.devices()[0].platform
        nchips = jax.device_count()
        per_chip_batch = 256 if platform == "tpu" else 8
        batch = per_chip_batch * nchips

        cache_dir = compilation.enable_persistent_cache(default_cache_dir())
        phase("build")
        step, state, b = build_step(batch)
        fl = step_flops(step, state, b)

        phase("compile")
        aot_dir = default_aot_dir()
        fp = compilation.topology_fingerprint(
            tag=compilation.config_tag("bench_resumable", batch))
        sig = compilation.abstract_signature((state, b))
        aot_path = None
        compiled = None
        if aot_dir:
            aot_path = os.path.join(
                aot_dir, f"bench_step-{fp}-{sig}{compilation.AOT_SUFFIX}")
            compiled = compilation.load_executable(aot_path, fingerprint=fp)
        loaded = compiled is not None
        if compiled is None:
            compiled = compilation.aot_compile(step, state, b)
            if aot_path:
                compilation.save_executable(
                    aot_path, compiled, fingerprint=fp)
        cm = compilation.compile_metrics()
        warmed_before = ledger["state"] in ("warmed", "measured")
        if ledger["state"] == "cold":
            ledger["state"] = "warmed"
        attempt["aot_loaded"] = loaded
        attempt["compile_seconds"] = cm["compile_seconds"]

        if (not (loaded or warmed_before)
                and deadline - time.monotonic() < args.measure_margin):
            # this attempt paid the cold half; bank it and yield the
            # window — the NEXT attempt starts at the measure phase
            phase("warmed")
            out = {
                "metric": "ResNet-50 train-step throughput "
                          f"({platform}, global batch {batch}, bf16)",
                "value": 0.0,
                "unit": "images/sec/chip",
                "vs_baseline": 0.0,
                "warmed": True,
                "phase": "warmed",
                "resumable": provenance(),
                "compile_seconds": cm["compile_seconds"],
                "cache_hits": cm["cache_hits"],
                "cache_misses": cm["cache_misses"],
                "compile_cache_dir": cache_dir,
                "aot_path": aot_path,
                "lint": lint_stamp(),
                "guard": guard_stamp(),
                "memory": memory_stamp(state),
            }
            print(json.dumps(out))
            # a warmed round is history too: the ledger row says this
            # window paid the cold half (value 0 but no error)
            append_run_record(out, fingerprint=fp)
            return 0

        phase("measure")
        dt = _measure_compiled(compiled, state, b, args.steps)
        ledger["state"] = "measured"
        phase("done")
        ips_per_chip = batch / dt / nchips
        vs = (ips_per_chip / BASELINE_IMAGES_PER_SEC_PER_CHIP
              if BASELINE_IMAGES_PER_SEC_PER_CHIP else 1.0)
        out = {
            "metric": "ResNet-50 train-step throughput "
                      f"({platform}, global batch {batch}, bf16)",
            "value": round(ips_per_chip, 2),
            "unit": "images/sec/chip",
            "vs_baseline": round(vs, 3),
            "mfu_pct": mfu_pct(fl, dt, nchips),
            "measure_steps": args.steps,
            "aot_loaded": loaded,
            "phase": "done",
            "resumable": provenance(),
            "compile_seconds": cm["compile_seconds"],
            "cache_hits": cm["cache_hits"],
            "cache_misses": cm["cache_misses"],
            "compile_seconds_saved": cm["compile_seconds_saved"],
            "compile_cache_dir": cache_dir,
            "lint": lint_stamp(),
            "guard": guard_stamp(),
            "memory": memory_stamp(state),
            "layout_pick": layout_pick_stamp(),
        }
        print(json.dumps(out))
        # the green-number path: this row is what item 1's first
        # defended trend row looks like (fingerprint-keyed baseline)
        append_run_record(out, fingerprint=fp)
        return 0
    except BaseException as e:  # noqa: BLE001 — always emit the JSON line
        traceback.print_exc(file=sys.stderr)
        err = f"{type(e).__name__}: {e}"
        attempt["error"] = err[:500]
        try:
            _write_json_atomic(args.ledger, ledger)
        except OSError:
            pass
        # error-path stamps are ALL wall-bounded: every one of them
        # reaches into the runtime (registry callbacks, memory_stats,
        # the picker's compiles) and the wedged backend that killed the
        # round must not also hang its own death report
        out = {
            "metric": "ResNet-50 train-step throughput",
            "value": 0.0,
            "unit": "images/sec/chip",
            "vs_baseline": 0.0,
            "error": err[:500],
            "phase": attempt["phase"],
            "retryable": retryable_error(attempt["phase"], err),
            "resumable": provenance(),
            "lint": lint_stamp_bounded(),
            "guard": guard_stamp_bounded(),
            # memory state at death: live HBM peak when available
            "memory": memory_stamp_bounded(),
            # what the picker WOULD have chosen here (wall-bounded —
            # a wedged backend's compile must not hang the error line)
            "layout_pick": layout_pick_stamp_bounded(),
        }
        print(json.dumps(out))
        # dead rounds are history too — NO fingerprint (computing one
        # calls jax.devices(), which is exactly what may be wedged)
        append_run_record(out)
        return 0


def _write_status(path, phase):
    """Phase marker + compile ledger for the parent: when the bounded
    subprocess dies mid-measurement, the last snapshot says whether the
    time went to backend init, compilation, or the measurement itself
    (and how many compiles the cache absorbed before death)."""
    if not path:
        return
    from fluxdistributed_tpu import compilation

    try:
        payload = {"phase": phase, **compilation.compile_metrics(),
                   "guard": guard_stamp(), "memory": memory_stamp()}
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        import os

        os.replace(tmp, path)
    except Exception:  # noqa: BLE001 — forensics must never kill the bench
        pass


def _measure():
    import os

    from fluxdistributed_tpu import compilation
    from fluxdistributed_tpu.obs import jaxmon

    jaxmon.install()  # compile/cache counters from the first compile on
    status_path = os.environ.get("BENCH_STATUS_FILE")
    # marker BEFORE cache enablement: namespacing the cache dir touches
    # jax.devices(), which on a tunneled TPU IS the grant wait — a death
    # here must report backend_init, not "unknown"
    _write_status(status_path, "backend_init")
    cache_dir = compilation.enable_persistent_cache(default_cache_dir())

    import jax

    platform = jax.devices()[0].platform
    nchips = jax.device_count()
    # A 64→512 sweep on v5e: 64/chip is ~15% slower; 128–512 are flat
    # within ~±5% (~2300 img/s).  256/chip sits mid-range and fits
    # ResNet-50 activations comfortably.
    per_chip_batch = 256 if platform == "tpu" else 8
    batch = per_chip_batch * nchips

    _write_status(status_path, "build")
    step, state, b = build_step(batch)
    # FLOP count before the timed loop: the donated state's buffers are
    # gone after the first step call, and lower() is a cheap local trace
    fl = step_flops(step, state, b)
    _write_status(status_path, "compile")
    dt, _ = time_compiled_step(
        step, state, b,
        on_compiled=lambda: _write_status(status_path, "measure"))
    cm = compilation.compile_metrics()
    _write_status(status_path, "done")

    ips_per_chip = batch / dt / nchips
    vs = (
        ips_per_chip / BASELINE_IMAGES_PER_SEC_PER_CHIP
        if BASELINE_IMAGES_PER_SEC_PER_CHIP
        else 1.0
    )
    return {
        "metric": f"ResNet-50 train-step throughput ({platform}, global batch {batch}, bf16)",
        "value": round(ips_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs, 3),
        "mfu_pct": mfu_pct(fl, dt, nchips),
        # cold-start ledger: where the wall time ahead of the timed loop
        # went, and how much of it the persistent cache absorbed
        "compile_seconds": cm["compile_seconds"],
        "cache_hits": cm["cache_hits"],
        "cache_misses": cm["cache_misses"],
        "compile_seconds_saved": cm["compile_seconds_saved"],
        "compile_cache_dir": cache_dir,
        # static-health stamp: the lint verdict this code measured under
        "lint": lint_stamp(),
        # robustness forensics: fault/watchdog/guard counters this
        # measurement accumulated (retries survived, stalls seen)
        "guard": guard_stamp(),
        # HBM forensics: static state bytes + live per-device memory
        # (peak included) when memory_stats() is live on this backend
        "memory": memory_stamp(state),
        # planner paired row: uniform vs planned modeled bubble for a
        # production-shaped LM on this box's static costs
        "pp_plan": pp_plan_stamp(),
        # auto-layout picker verdict: chosen dp x fsdp x tp layout for
        # the bench-shaped LM on THIS topology, with each candidate's
        # headroom + collective-ledger figures (parallel/layout.py)
        "layout_pick": layout_pick_stamp(),
    }


def main():
    # The driver records rc and the last JSON line; NOTHING may prevent
    # that line from being printed with rc=0:
    # * transient runtime failures (e.g. "remote_compile: read body:
    #   response body closed", BENCH_r02) -> retry;
    # * a hung backend init (an unavailable tunneled chip can block
    #   jax.devices() in C for 25+ minutes, 2026-07-30) -> the
    #   measurement runs in a BOUNDED SUBPROCESS the parent can always
    #   give up on, in-process code cannot interrupt that hang.
    import os
    import subprocess

    if "--resumable" in sys.argv:
        argv = [a for a in sys.argv[1:] if a != "--resumable"]
        sys.exit(resumable_main(argv))
    if "--one" in sys.argv:
        print(json.dumps(_measure()))
        return

    last_err = "unknown"
    # the child drops phase/compile snapshots here so a timeout is
    # diagnosable (compile-bound vs hardware-bound) from the error JSON
    status_file = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".bench_status.json")
    child_env = {**os.environ, "BENCH_STATUS_FILE": status_file}
    try:
        os.remove(status_file)  # never attribute a previous run's status
    except OSError:
        pass
    deadline = time.monotonic() + 420  # leave headroom under driver timeouts
    for attempt in range(3):
        budget = max(60, int(deadline - time.monotonic()) + 180)
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--one"],
                capture_output=True, text=True, timeout=budget,
                env=child_env,
            )
            sys.stderr.write(p.stderr[-2000:])
            for line in reversed(p.stdout.strip().splitlines()):
                try:
                    parsed = json.loads(line)
                except (json.JSONDecodeError, ValueError):
                    continue
                print(line)
                # the child ran --one (no ledger append of its own):
                # mirror its verdict into the cross-run history here
                if isinstance(parsed, dict):
                    append_run_record(parsed)
                return
            last_err = f"rc={p.returncode}, no JSON line; stderr tail: " + \
                p.stderr.strip()[-300:]
        except subprocess.TimeoutExpired:
            last_err = f"measurement subprocess timed out after {budget}s"
        except Exception as e:  # noqa: BLE001 — any failure is retryable here
            last_err = f"{type(e).__name__}: {e}"
            traceback.print_exc(file=sys.stderr)
        if attempt == 2 or time.monotonic() > deadline:
            print("bench: giving up, emitting error JSON", file=sys.stderr)
            break
        print(f"bench attempt {attempt + 1} failed; retrying", file=sys.stderr)
        time.sleep(5)
    # fold the child's last phase/compile snapshot into the error JSON:
    # a zero artifact then says WHERE the attempt died (backend_init /
    # build / compile / measure) and what the cold start had cost by
    # then — the difference between "the chip never granted" and "the
    # grant window was eaten by compilation"
    status = {}
    try:
        with open(status_file) as f:
            status = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        pass
    out = {
        "metric": "ResNet-50 train-step throughput",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "error": str(last_err),
        "phase": status.get("phase", "unknown"),
        # phase-aware transient/permanent classification: the watcher
        # backs off and retries ONLY on retryable errors — an unknown
        # phase means the child died before its first marker, i.e. in
        # backend territory, which classifies retryable via the
        # signature list
        "retryable": retryable_error(
            status.get("phase", "backend_init"), str(last_err)),
        "compile_seconds": status.get("compile_seconds", 0.0),
        "cache_hits": status.get("cache_hits", 0),
        "cache_misses": status.get("cache_misses", 0),
        # the error artifact carries the same static-health stamp, so a
        # timeout round still records whether the code was lint-clean
        # (wall-bounded like every error-path stamp below: the error
        # JSON must outrun whatever wedged the round)
        "lint": lint_stamp_bounded(),
        # the CHILD's robustness counters at its last status snapshot —
        # a dead round records the faults/stalls it saw before dying
        "guard": status.get("guard", guard_stamp_bounded()),
        # and the CHILD's memory state at its last snapshot — dead hw
        # rounds record the HBM picture at death, not the parent's
        "memory": status.get("memory", memory_stamp_bounded()),
        # the layout the picker would have chosen on this topology
        # (wall-bounded: the parent error path follows a child that
        # may have died on a wedged backend)
        "layout_pick": layout_pick_stamp_bounded(),
    }
    # If a background probe loop has been retrying the chip (the r4+
    # availability workflow: benchmarks/hw_watch.sh, docs/benchmarks.md),
    # attach its evidence so a zero artifact shows the outage was
    # continuously probed, not unattended.
    here = os.path.dirname(os.path.abspath(__file__))
    for log in (os.path.join(here, "benchmarks", "hw", "watch.log"),
                os.path.join(here, ".bench_probe_r4.log")):
        try:
            with open(log) as f:
                lines = [ln.strip() for ln in f if ln.strip()]
        except OSError:
            continue
        if lines:
            out.setdefault("probe_logs", {})[os.path.basename(log)] = {
                "lines": len(lines), "last": lines[-1][:200],
            }
            # flat legacy keys (pre-r4 schema) kept alongside probe_logs
            # for one round so older verdict tooling keeps parsing.
            # Deliberately first-log-found: watch.log (canonical watcher
            # evidence) when present, else the r4 probe log — present
            # whenever ANY probe evidence exists.  "attempts" is
            # historically a raw line count, not parsed attempt rows.
            out.setdefault("probe_attempts", len(lines))
            out.setdefault("probe_last", lines[-1][:200])
    print(json.dumps(out))
    # the dead round goes on record too — error rows are excluded from
    # baselines but are exactly what --postmortem and item 1 read
    append_run_record(out)


if __name__ == "__main__":
    main()
