"""bin/fit.py — the memory/comms fit checker: headroom ranking,
oversized-config rejection, the baseline --check workflow, and the
topology gate, all driven through main(argv) in-process.
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fit():
    spec = importlib.util.spec_from_file_location(
        "fdtpu_fit_cli", os.path.join(REPO, "bin", "fit.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def fit():
    return _fit()


@pytest.fixture()
def artifact(tmp_path):
    """A v2 artifact with one small and one provably-oversized
    variant, fingerprinted for THIS process so the topology gate
    passes."""
    from fluxdistributed_tpu.compilation import topology_fingerprint
    from fluxdistributed_tpu.obs.profile import Profile, describe_topology

    def mem(peak):
        return {"memory": {"peak_bytes": peak, "argument_bytes": peak,
                           "output_bytes": 0, "temp_bytes": 0,
                           "alias_bytes": 0,
                           "generated_code_bytes": 0}}

    prof = Profile(
        fingerprint=topology_fingerprint(),
        topology=describe_topology(),
        memory={"state": None, "step": None,
                "variants": {"small": mem(1_000),
                             "huge": mem(10**15),
                             "dark": {"memory": None}}},
        comms={"step": {}, "variants": {}},
    )
    path = tmp_path / "fit.profile.json"
    prof.save(str(path))
    return str(path)


def test_ranking_and_fit_verdicts(fit, artifact, capsys):
    rc = fit.main(["--profile", artifact, "--hbm-bytes", "1e6",
                   "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    rows = {r["variant"]: r for r in out["rows"]}
    assert rows["small"]["fits"] is True
    assert rows["small"]["headroom_bytes"] == 1_000_000 - 1_000
    assert rows["huge"]["fits"] is False
    assert rows["dark"]["fits"] is None  # unknown is not "fits"
    # ranking: most headroom first, unknowns last
    order = [r["variant"] for r in out["rows"]]
    assert order == ["small", "huge", "dark"]


def test_require_rejects_oversized_and_accepts_fitting(fit, artifact):
    # the acceptance bar: a provably-oversized config is REJECTED...
    rc = fit.main(["--profile", artifact, "--hbm-bytes", "1e6",
                   "--require", "huge"])
    assert rc == 3
    # ...while a fitting one ranks and passes
    rc = fit.main(["--profile", artifact, "--hbm-bytes", "1e6",
                   "--require", "small"])
    assert rc == 0
    # unknown variant name is a usage error, not a silent pass
    rc = fit.main(["--profile", artifact, "--hbm-bytes", "1e6",
                   "--require", "nope"])
    assert rc == 2
    # a variant with no memory model does NOT pass --require
    rc = fit.main(["--profile", artifact, "--hbm-bytes", "1e6",
                   "--require", "dark"])
    assert rc == 3


def test_no_budget_on_cpu_is_informational(fit, artifact, capsys):
    rc = fit.main(["--profile", artifact])
    assert rc == 0
    out = capsys.readouterr().out
    assert "no HBM budget" in out and "--hbm-bytes" in out


def test_baseline_check_workflow(fit, artifact, tmp_path, capsys):
    base = str(tmp_path / "membase.json")
    assert fit.main(["--profile", artifact, "--update-baseline",
                     "--baseline", base]) == 0
    doc = json.load(open(base))
    assert set(doc["variants"]) == {"small", "huge"}  # dark: no model

    # clean re-check
    assert fit.main(["--profile", artifact, "--check",
                     "--baseline", base, "--hbm-bytes", "1e6"]) == 0
    capsys.readouterr()

    # regress one variant beyond tolerance → exit 1 naming it
    doc["variants"]["small"]["peak_bytes"] = 100
    json.dump(doc, open(base, "w"))
    rc = fit.main(["--profile", artifact, "--check",
                   "--baseline", base, "--hbm-bytes", "1e6"])
    assert rc == 1
    assert "small" in capsys.readouterr().out

    # a variant missing from the baseline (new) also fails the check
    del doc["variants"]["huge"]
    doc["variants"]["small"]["peak_bytes"] = 1_000
    json.dump(doc, open(base, "w"))
    rc = fit.main(["--profile", artifact, "--check",
                   "--baseline", base, "--hbm-bytes", "1e6"])
    assert rc == 1
    assert "not covered" in capsys.readouterr().out

    # missing baseline file under --check = usage error
    assert fit.main(["--profile", artifact, "--check", "--baseline",
                     str(tmp_path / "absent.json")]) == 2


def test_topology_gate(fit, tmp_path, capsys):
    from fluxdistributed_tpu.obs.profile import Profile

    prof = Profile(fingerprint="deadbeefdeadbeef",
                   topology={"platform": "tpu", "device_count": 256})
    path = str(tmp_path / "foreign.json")
    prof.save(path)
    with pytest.raises(SystemExit, match="does not match"):
        fit.main(["--profile", path, "--hbm-bytes", "1e6"])
    # --allow-mismatch downgrades the gate to a loud warning
    rc = fit.main(["--profile", path, "--hbm-bytes", "1e6",
                   "--allow-mismatch"])
    assert rc == 0
    assert "topology gate skipped" in capsys.readouterr().err


def test_committed_baseline_covers_every_registered_variant():
    """The CI-gated invariant: the committed memory baseline names
    every program the variant registry builds — a newly registered
    variant without a baseline entry must fail the --check before it
    reaches CI."""
    from fluxdistributed_tpu.analysis.variants import (
        VARIANT_BUILDERS, variant_names)

    base = json.load(open(os.path.join(
        REPO, "fluxdistributed_tpu", "analysis", "memory_baseline.json")))
    covered = set(base["variants"])
    # per-builder program names are prefixed by the registry name
    # (serve pools register several programs per builder)
    for name in variant_names():
        assert any(v == name or v.startswith(name + ":")
                   for v in covered), (
            f"variant {name!r} has no memory-baseline entry — run "
            "bin/fit.py --collect ... --update-baseline")
    assert base["schema"] == "fdtpu-membaseline/v1"
    assert VARIANT_BUILDERS  # the registry itself stays non-empty
