"""Declarative sharding-rules engine (parallel/rules.py).

The headline contracts:

* PARITY — the committed rule tables reproduce every hand-built spec
  builder leaf-for-leaf (dp/zero1 = replicated, fsdp = the shape walk,
  lm/vit tp = the Megatron callables, fsdp x tp = the hybrid special
  case), so the refactor cannot move a single leaf's placement — the
  old AOT keys and the memory baseline survive.
* FALLBACK HONESTY — unmatched leaves replicate, but dead rules and
  large silently-replicating leaves are reported (and raise under
  strict=True).
* VALIDATION — unknown axes and indivisible shards are rejected
  eagerly, before any memory commits, with the offending rule/leaf
  named.
* END-TO-END — a ~10-line rule list shards a model through
  prepare_training with NO hand-written spec code, at loss parity
  with the hand-built variant.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from fluxdistributed_tpu import mesh as mesh_lib, optim
from fluxdistributed_tpu.parallel import dp, fsdp, rules, tp


def _spec_leaves(tree):
    return jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda x: x is None or isinstance(x, P))[0]


def assert_spec_trees_equal(a, b, ctx=""):
    fa, fb = _spec_leaves(a), _spec_leaves(b)
    assert len(fa) == len(fb), (ctx, len(fa), len(fb))
    for (pa, sa), (_, sb) in zip(fa, fb):
        assert sa == sb, (ctx, jax.tree_util.keystr(pa), sa, sb)


def _lm_params(**kw):
    from fluxdistributed_tpu.models.transformer_lm import TransformerLM

    model = TransformerLM(vocab=32, dim=16, depth=2, num_heads=4,
                          mlp_dim=32, **kw)
    return jax.eval_shape(
        lambda s: model.init(jax.random.PRNGKey(0), s, train=False),
        jax.ShapeDtypeStruct((1, 8), "int32"))["params"]


def _cnn_state():
    from fluxdistributed_tpu.models.simple import SimpleCNN

    model = SimpleCNN(num_classes=4, features=8)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8, 8, 3), np.float32),
                        train=True)["params"]
    return dp.TrainState.create(params, optim.adam(1e-3))


@pytest.fixture(scope="module")
def mesh24():
    return mesh_lib.make_mesh(
        {mesh_lib.DATA_AXIS: 2, mesh_lib.MODEL_AXIS: 4})


# ---------------------------------------------------------------- parity

def test_dp_table_is_replicated_everywhere():
    """The dp/zero1 placement as the EMPTY table: every leaf P()."""
    params = _lm_params()
    specs = rules.match_partition_rules(rules.dp_rules(), params)
    for pth, s in _spec_leaves(specs):
        assert s == P(), jax.tree_util.keystr(pth)


@pytest.mark.parametrize("variant", ["plain", "gqa", "swiglu", "untied"])
def test_lm_tp_table_matches_hand_built(variant, mesh24):
    kw = {"plain": {}, "gqa": {"num_kv_heads": 2},
          "swiglu": {"mlp": "swiglu"},
          "untied": {"tie_embeddings": False}}[variant]
    params = _lm_params(**kw)
    hand = tp.param_specs(params, tp.lm_tp_rules())
    table = rules.match_partition_rules(
        rules.lm_tp_rules_table(), params, mesh=mesh24)
    assert_spec_trees_equal(hand, table, variant)


def test_vit_tp_table_matches_hand_built(mesh24):
    from fluxdistributed_tpu.models.vit import ViT

    model = ViT(patch=4, depth=2, dim=16, num_heads=4, mlp_dim=32,
                num_classes=4)
    params = jax.eval_shape(
        lambda s: model.init(jax.random.PRNGKey(0), s, train=False),
        jax.ShapeDtypeStruct((1, 8, 8, 3), "float32"))["params"]
    hand = tp.param_specs(params, tp.vit_tp_rules())
    table = rules.match_partition_rules(
        rules.vit_tp_rules_table(), params, mesh=mesh24)
    assert_spec_trees_equal(hand, table, "vit")


def test_fsdp_table_matches_hand_built_state_tree():
    """ONE ShardLargest rule == the whole fsdp_specs shape walk, for
    the FULL TrainState (params + Adam moments broadcast from their
    param; model_state/step replicated)."""
    state = _cnn_state()
    mesh = mesh_lib.data_mesh(8)
    hand = fsdp.fsdp_specs(state, mesh)
    p_specs = rules.match_partition_rules(
        rules.fsdp_rules(axis=mesh_lib.DATA_AXIS,
                         min_size=fsdp.MIN_SHARD_ELEMS),
        state.params, mesh=mesh)
    derived = rules.train_state_specs(state, p_specs)
    assert_spec_trees_equal(hand, derived, "fsdp")


def test_fsdp_overlay_matches_hybrid_special_case(mesh24):
    """rules table + with_fsdp == hybrid_fsdp_tp_specs, leaf-for-leaf
    — the 2-D composition, derived instead of special-cased."""
    params = _lm_params()
    hand = fsdp.hybrid_fsdp_tp_specs(params, mesh24, tp.lm_tp_rules())
    base = rules.match_partition_rules(
        rules.lm_tp_rules_table(), params, mesh=mesh24)
    derived = rules.with_fsdp(base, params, mesh24,
                              axis=mesh_lib.DATA_AXIS,
                              min_size=fsdp.MIN_SHARD_ELEMS)
    assert_spec_trees_equal(hand, derived, "hybrid")


# ------------------------------------------------------- matcher semantics

def test_first_match_wins_and_scalars_replicate():
    params = {"block": {"qkv": {"kernel": np.zeros((8, 8))}},
              "scale": np.zeros(())}
    specs = rules.match_partition_rules(
        [(r"qkv/kernel$", P(None, mesh_lib.MODEL_AXIS)),
         (r"kernel$", P(mesh_lib.DATA_AXIS, None)),
         # scalars replicate before any rule is consulted
         (r"scale$", P(mesh_lib.DATA_AXIS))],
        params)
    assert specs["block"]["qkv"]["kernel"] == P(None, mesh_lib.MODEL_AXIS)
    assert specs["scale"] == P()


def test_fallback_report_and_strict():
    params = {"big": np.zeros((4096, 4)), "small": np.zeros((8,)),
              "hit": np.zeros((16, 16))}
    rep = rules.RuleReport({}, [], [], [])
    rules.match_partition_rules(
        [(r"hit$", P()), (r"matches_nothing$", P())], params,
        report=rep)
    assert rep.dead == ["matches_nothing$"]
    assert {p for p, _ in rep.unmatched} == {"big", "small"}
    assert [p for p, _ in rep.large_unmatched] == ["big"]
    with pytest.raises(ValueError, match="fell to replication"):
        rules.match_partition_rules(
            [(r"hit$", P())], params, strict=True)


def test_rule_report_never_needs_a_mesh():
    rep = rules.rule_report(rules.fsdp_rules(), {"w": np.zeros((64, 64))})
    assert rep.matched[r".*"] == ["w"] and rep.dead == []


# ------------------------------------------------------------- validation

def test_unknown_axis_rejected_eagerly(mesh24):
    with pytest.raises(ValueError, match="bogus.*not on the mesh"):
        rules.match_partition_rules(
            [(r".*", P("bogus"))], {"w": np.zeros((8, 8))}, mesh=mesh24)
    with pytest.raises(ValueError, match="not on the mesh"):
        rules.match_partition_rules(
            [(r".*", rules.ShardLargest("bogus"))],
            {"w": np.zeros((8, 8))}, mesh=mesh24)


def test_validate_specs_divisibility(mesh24):
    shapes = {"w": np.zeros((6, 8))}  # 6 % model(4) != 0
    specs = {"w": P(mesh_lib.MODEL_AXIS, None)}
    with pytest.raises(ValueError, match="not divisible"):
        rules.validate_specs(specs, shapes, mesh24, where="toy")
    # adam-style tuple state must not be mistaken for a shape literal
    shapes = {"w": (np.zeros((8, 8)), np.zeros((8, 8)))}
    specs = {"w": (P(mesh_lib.MODEL_AXIS, None),) * 2}
    rules.validate_specs(specs, shapes, mesh24, where="toy")


def test_bad_rule_value_type():
    with pytest.raises(TypeError, match="neither a PartitionSpec"):
        rules.match_partition_rules(
            [(r".*", "data")], {"w": np.zeros((8, 8))})


# ----------------------------------------------------------- end-to-end

def test_ten_line_table_trains_at_loss_parity():
    """The acceptance bar: a ~10-line rule list shards a model through
    prepare_training with NO hand-written spec code, at loss parity
    with the hand-built fsdp variant (same math, different axis name —
    allclose, not bitwise: GSPMD may order reductions differently)."""
    from fluxdistributed_tpu.data.synthetic import SyntheticDataset
    from fluxdistributed_tpu.models.simple import SimpleCNN
    from fluxdistributed_tpu.train.trainer import prepare_training

    model = SimpleCNN(num_classes=4, features=8)
    ds = SyntheticDataset(nsamples=64, nclasses=4, shape=(8, 8, 3))

    def losses(**kw):
        task = prepare_training(model, ds, optim.adam(1e-3),
                                batch_size=16, cycles=3, seed=0, **kw)
        out = []
        state = task.state
        for batch in task.loader:
            state, metrics = task.step_fn(state, batch)
            out.append(float(metrics["loss"]))
        return out

    hand = losses(spmd="fsdp")
    derived = losses(layout="fsdp")  # the ONE-rule fsdp table
    assert np.allclose(hand, derived, rtol=2e-4, atol=2e-5), (
        hand, derived)


def test_layout_conflicts_rejected():
    from fluxdistributed_tpu.data.synthetic import SyntheticDataset
    from fluxdistributed_tpu.models.simple import SimpleCNN
    from fluxdistributed_tpu.train.trainer import prepare_training

    model = SimpleCNN(num_classes=4, features=8)
    ds = SyntheticDataset(nsamples=64, nclasses=4, shape=(8, 8, 3))
    with pytest.raises(ValueError, match="cannot combine with spmd"):
        prepare_training(model, ds, optim.adam(1e-3), layout="fsdp",
                         spmd="fsdp", batch_size=16, cycles=1)
    with pytest.raises(ValueError, match="ZeRO-3 placement subsumes"):
        prepare_training(model, ds, optim.adam(1e-3), layout="fsdp",
                         zero1=True, batch_size=16, cycles=1)
    with pytest.raises(ValueError, match="divisible by the"):
        prepare_training(model, ds, optim.adam(1e-3), layout="dp_fsdp",
                         batch_size=12, cycles=1)


def test_layout_over_device_subset_mesh():
    """A layout + mesh built over a device SUBSET resolves against the
    mesh's own device count, not the process's (review regression)."""
    import jax

    from fluxdistributed_tpu.data.synthetic import SyntheticDataset
    from fluxdistributed_tpu.models.simple import SimpleCNN
    from fluxdistributed_tpu.parallel.layout import Layout
    from fluxdistributed_tpu.train.trainer import prepare_training

    lay = Layout("dp_fsdp_4", dp=2, fsdp=2)
    mesh = lay.build_mesh(devs=jax.devices()[:4])
    model = SimpleCNN(num_classes=4, features=8)
    ds = SyntheticDataset(nsamples=64, nclasses=4, shape=(8, 8, 3))
    task = prepare_training(model, ds, optim.adam(1e-3), layout=lay,
                            mesh=mesh, batch_size=16, cycles=1)
    _, m = task.step_fn(task.state, next(iter(task.loader)))
    assert np.isfinite(float(m["loss"]))
