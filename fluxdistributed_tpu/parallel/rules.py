"""Declarative sharding rules: a ~10-line regex-on-path rule table turns
into a full PartitionSpec tree for ANY model.

Every parallelism variant used to hand-build its PartitionSpecs per
model (``tp.lm_tp_rules`` / ``tp.vit_tp_rules`` as Python callables,
``fsdp.fsdp_specs`` as a shape walk), so each new model or mesh shape
cost bespoke spec code and nothing composed — ROADMAP item 3's wall.
This module replaces that with DATA:

* :func:`match_partition_rules` — EasyLM-style (SNIPPETS.md [3]): walk
  the param tree, '/'-join each leaf path, take the FIRST rule whose
  regex ``re.search``-matches, and use its value as the leaf's
  PartitionSpec.  Scalars and single-element leaves always replicate.
* :class:`ShardLargest` — a shape-driven rule value (the paranum-style
  size threshold, SNIPPETS.md [2], generalized by ``fsdp.fsdp_leaf_
  spec``): shard the leaf's largest still-unsharded divisible dim over
  one mesh axis.  This is how ZeRO-style parameter/optimizer sharding
  (arXiv:2004.13336 extended to ZeRO-3 placement) becomes ONE rule —
  ``(".*", ShardLargest(mesh.FSDP_AXIS))`` — instead of a per-model
  walk, and how it composes with tensor-parallel rules: a
  :func:`with_fsdp` overlay applies it on top of an existing spec
  tree's leftover dims (the 2-D/3-D recipe).
* **Fallback**: an unmatched leaf replicates (``P()``).  That is the
  safe default but also the silent memory trap — a 4 GB embedding
  falling to replication fits nowhere — so every resolution also
  produces a :class:`RuleReport` naming dead rules and large unmatched
  leaves (``strict=True`` raises on the latter; fdtpu-lint's FDT108
  checks the committed tables against registered probe models).
* **Validation**: :func:`validate_rules` rejects axis names not
  declared on the mesh, and :func:`validate_specs` runs the spec tree
  through ``analysis.jaxpr_checks.check_spec_tree`` (axis exists +
  divisibility) against real leaf shapes BEFORE any memory is
  committed.

The hand-built variants are reproducible as committed tables
(:data:`RULE_TABLES`) whose derived trees match the legacy builders
leaf-for-leaf — parity-pinned by tests/test_rules.py so the old AOT
keys and the memory baseline survive this refactor.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .. import mesh as mesh_lib

Pytree = Any

__all__ = [
    "ShardLargest",
    "Rule",
    "RuleReport",
    "RuleTable",
    "RULE_TABLES",
    "FALLBACK_MIN_SIZE",
    "match_partition_rules",
    "with_fsdp",
    "rule_report",
    "validate_rules",
    "validate_specs",
    "train_state_specs",
    "dp_rules",
    "fsdp_rules",
    "lm_tp_rules_table",
    "vit_tp_rules_table",
    "rules_for_model",
    "registered_rule_tables",
]

#: an UNMATCHED leaf at or above this many elements falling to
#: replication is reported (and rejected under ``strict=True``) — the
#: same scale as ``fsdp.MIN_SHARD_ELEMS``: below it, replication is the
#: right answer, not a trap
FALLBACK_MIN_SIZE = 2 ** 11


@dataclasses.dataclass(frozen=True)
class ShardLargest:
    """Shape-driven rule value: shard the leaf's largest
    still-unsharded dim divisible by the axis size over ``axis``
    (``fsdp.fsdp_leaf_spec`` semantics — ties break toward the
    trailing dim; leaves under ``min_size`` elements, or with no
    divisible dim, keep their base spec).  Resolution needs a mesh
    (the axis size), which :func:`match_partition_rules` provides."""

    axis: str = mesh_lib.FSDP_AXIS
    min_size: int = FALLBACK_MIN_SIZE


#: one rule: (regex searched against the '/'-joined leaf path, value).
#: The value is a PartitionSpec or a ShardLargest.
Rule = Tuple[str, Any]


@dataclasses.dataclass
class RuleReport:
    """What a rule resolution actually did — the honesty record behind
    the replication fallback (and FDT108's input)."""

    #: rule pattern → leaf paths it decided
    matched: dict
    #: rule patterns that decided NO leaf
    dead: list
    #: (path, elements) for every unmatched non-scalar leaf (fell to
    #: replication)
    unmatched: list
    #: the subset of ``unmatched`` at/above the size threshold — the
    #: silent-replication trap FDT108 flags
    large_unmatched: list


def _leaf_path(kp) -> str:
    return "/".join(
        str(getattr(k, "key", getattr(k, "name", getattr(k, "idx", k))))
        for k in kp)


def _resolve_value(value, shape, mesh: Optional[Mesh], base: P = None):
    if isinstance(value, ShardLargest):
        from .fsdp import fsdp_leaf_spec

        if mesh is None:
            raise ValueError(
                "a ShardLargest rule value needs a mesh to resolve "
                "(its axis size drives divisibility) — pass mesh= to "
                "match_partition_rules")
        if value.axis not in mesh.shape:
            raise ValueError(
                f"ShardLargest axis {value.axis!r} is not on the mesh "
                f"(axes: {sorted(dict(mesh.shape))})")
        return fsdp_leaf_spec(
            shape, value.axis, int(mesh.shape[value.axis]),
            min_size=value.min_size, base=base)
    if value is None:
        return P()
    if isinstance(value, P):
        return value
    raise TypeError(
        f"rule value {value!r} is neither a PartitionSpec nor a "
        "ShardLargest")


def match_partition_rules(
    rules: Sequence[Rule],
    params: Pytree,
    *,
    mesh: Optional[Mesh] = None,
    min_size: int = FALLBACK_MIN_SIZE,
    strict: bool = False,
    report: Optional[RuleReport] = None,
) -> Pytree:
    """PartitionSpec tree for ``params`` from a regex rule table.

    First match wins (order the specific patterns before the broad
    ones); scalars/single-element leaves replicate unconditionally;
    unmatched leaves fall to replication, recorded in ``report`` (pass
    a fresh :class:`RuleReport` to collect it; ``strict=True``
    additionally raises when an unmatched leaf has >= ``min_size``
    elements — the silent-replication trap).  ``mesh`` is required
    when any rule value is a :class:`ShardLargest` and is also used to
    pre-validate axis names via :func:`validate_rules`.
    """
    import jax

    if mesh is not None:
        validate_rules(rules, mesh)
    compiled = [(re.compile(pat), pat, val) for pat, val in rules]
    rep = report if report is not None else RuleReport({}, [], [], [])
    for _, pat, _ in compiled:
        rep.matched.setdefault(pat, [])

    def decide(kp, leaf):
        path = _leaf_path(kp)
        shape = np.shape(leaf)
        if len(shape) == 0 or int(np.prod(shape)) <= 1:
            return P()
        for rx, pat, val in compiled:
            if rx.search(path) is not None:
                rep.matched[pat].append(path)
                return _resolve_value(val, shape, mesh)
        n = int(np.prod(shape))
        rep.unmatched.append((path, n))
        if n >= min_size:
            rep.large_unmatched.append((path, n))
        return P()

    specs = jax.tree_util.tree_map_with_path(decide, params)
    rep.dead = [pat for _, pat, _ in compiled if not rep.matched[pat]]
    if strict and rep.large_unmatched:
        worst = ", ".join(
            f"{p} ({n} elems)" for p, n in rep.large_unmatched[:5])
        raise ValueError(
            f"{len(rep.large_unmatched)} unmatched leaf(ves) of >= "
            f"{min_size} elements fell to replication: {worst} — add a "
            "rule (or a ShardLargest catch-all), or drop strict=True "
            "if replication is intended")
    return specs


def with_fsdp(
    specs: Pytree,
    params: Pytree,
    mesh: Mesh,
    axis: str = mesh_lib.FSDP_AXIS,
    min_size: int = FALLBACK_MIN_SIZE,
) -> Pytree:
    """Overlay ZeRO-style fully-sharded placement on an existing spec
    tree: every large leaf's largest still-unsharded dim is sharded
    over ``axis`` (existing entries — e.g. tensor-parallel dims — are
    preserved).  ``rules → with_fsdp`` is the 2-D/3-D composition the
    hand-built ``fsdp.hybrid_fsdp_tp_specs`` special-cased for TP."""
    import jax

    from .fsdp import fsdp_leaf_spec

    n = int(mesh.shape[axis])
    return jax.tree_util.tree_map(
        lambda spec, leaf: fsdp_leaf_spec(
            np.shape(leaf), axis, n, min_size=min_size, base=spec),
        specs, params, is_leaf=lambda x: isinstance(x, P))


def rule_report(rules: Sequence[Rule], params: Pytree,
                min_size: int = FALLBACK_MIN_SIZE) -> RuleReport:
    """Resolve ``rules`` against ``params`` purely for the report —
    dead rules + unmatched leaves (FDT108's engine).  Shape-driven
    values resolve as replicated here (no mesh): only MATCHING is
    reported, not the final placement."""
    rep = RuleReport({}, [], [], [])
    safe = [(pat, P() if isinstance(val, ShardLargest) else val)
            for pat, val in rules]
    match_partition_rules(
        safe, params, min_size=min_size, report=rep)
    return rep


def _spec_axes(spec) -> Tuple[str, ...]:
    out = []
    for entry in spec:
        if entry is None:
            continue
        for a in (entry if isinstance(entry, (tuple, list)) else (entry,)):
            out.append(str(a))
    return tuple(out)


def validate_rules(rules: Sequence[Rule], mesh: Mesh) -> None:
    """Reject rule values naming axes the mesh does not declare —
    BEFORE tracing, with the offending rule named (GSPMD's own error
    comes at compile time and names neither)."""
    axes = set(dict(mesh.shape))
    for pat, val in rules:
        if isinstance(val, ShardLargest):
            bad = () if val.axis in axes else (val.axis,)
        elif val is None:
            bad = ()
        elif isinstance(val, P):
            bad = tuple(a for a in _spec_axes(val) if a not in axes)
        else:
            raise TypeError(
                f"rule {pat!r} value {val!r} is neither a PartitionSpec "
                "nor a ShardLargest")
        if bad:
            raise ValueError(
                f"rule {pat!r} names mesh axis(es) {sorted(set(bad))} "
                f"not on the mesh (axes: {sorted(axes)}) — source axis "
                "names from fluxdistributed_tpu.mesh constants and "
                "build the mesh with those axes")


def validate_specs(specs: Pytree, shapes: Pytree, mesh: Mesh,
                   where: str = "rules") -> None:
    """Run a derived spec tree through the lint suite's
    ``check_spec_tree`` (axis exists + sharded dims divisible) and
    raise ONE ValueError carrying every finding — the same validation
    a jaxpr-layer sweep would report, applied eagerly at layout-build
    time where the fix is one rule away.

    The two trees are aligned leaf-by-leaf HERE (flattening ``shapes``
    with arrays as leaves) because ``check_spec_tree``'s raw-tuple
    heuristic would otherwise mistake tuple-structured state — Adam's
    ``(m, v)`` pairs — for shape literals."""
    import jax
    from jax.tree_util import keystr

    from ..analysis.jaxpr_checks import check_spec_tree

    is_spec = lambda x: x is None or isinstance(x, P)  # noqa: E731
    sflat = jax.tree_util.tree_flatten_with_path(specs, is_leaf=is_spec)[0]
    aflat = jax.tree_util.tree_flatten_with_path(
        shapes, is_leaf=lambda x: x is None)[0]
    if len(sflat) != len(aflat):
        raise ValueError(
            f"{where}: spec tree has {len(sflat)} leaves but the state "
            f"tree has {len(aflat)} — regenerate the specs from the "
            "live state tree")
    specs_d, shapes_d = {}, {}
    for i, ((pth, spec), (_, leaf)) in enumerate(zip(sflat, aflat)):
        if leaf is None or spec is None:
            continue
        key = f"{i}{keystr(pth)}"
        specs_d[key] = spec
        shapes_d[key] = tuple(np.shape(leaf))
    findings = check_spec_tree(shapes_d, specs_d, mesh, where=where)
    if findings:
        msgs = "; ".join(f.message for f in findings[:8])
        raise ValueError(
            f"rule-derived specs failed validation ({len(findings)} "
            f"finding(s)): {msgs}")


def train_state_specs(state, p_specs: Pytree):
    """A ``TrainState`` of specs from a param spec tree: optimizer
    state broadcast from its param's spec (``tp.broadcast_prefix`` —
    Adam moments share the param's shape, so the shape-driven and
    broadcast answers agree), mutable model state and the step counter
    replicated.  The same recipe ``tp.state_specs`` uses — shared so a
    rule-derived tree drops into every consumer a hand-built one
    could."""
    from .tp import state_specs

    return state_specs(state, p_specs)


# -- committed rule tables ---------------------------------------------------
#
# The hand-built variants, as data.  Each table reproduces its legacy
# builder's spec tree leaf-for-leaf (parity-pinned in
# tests/test_rules.py).  Patterns are ordered specific-first: the
# matcher takes the FIRST hit ("qkv/kernel$" must win before a
# hypothetical broad "kernel$").


def dp_rules() -> list:
    """Plain data parallelism: no parameter sharding at all — the
    empty table (every leaf falls to replication, which IS the dp/
    zero1 placement; ZeRO-1's flat optimizer shards are an internal
    re-layout of the update, not a spec-tree property)."""
    return []


def fsdp_rules(axis: str = mesh_lib.FSDP_AXIS,
               min_size: int = FALLBACK_MIN_SIZE) -> list:
    """ZeRO-3 placement as ONE rule: every large leaf's largest
    divisible dim shards over ``axis``.  With ``axis=mesh.DATA_AXIS``
    on a 1-D mesh this reproduces ``fsdp.fsdp_specs`` exactly."""
    return [(r".*", ShardLargest(axis, min_size=min_size))]


def lm_tp_rules_table(model_axis: str = mesh_lib.MODEL_AXIS,
                      shard_vocab: bool = True) -> list:
    """``tp.lm_tp_rules`` as data — the Megatron transformer recipe in
    13 lines: qkv/q/kv column-sharded over heads, attention out
    row-sharded, MLP up (gelu Dense_0 / swiglu gate+up) column- and
    down (Dense_1/down) row-sharded, vocab embedding sharded."""
    rules = []
    if shard_vocab:
        rules.append((r"embed/embedding$", P(model_axis, None)))
    rules += [
        (r"qkv/kernel$", P(None, None, model_axis, None)),
        (r"qkv/bias$", P(None, model_axis, None)),
        (r"kv/kernel$", P(None, None, model_axis, None)),
        (r"kv/bias$", P(None, model_axis, None)),
        (r"q/kernel$", P(None, model_axis, None)),
        (r"q/bias$", P(model_axis, None)),
        (r"out/kernel$", P(model_axis, None, None)),
        (r"head/kernel$", P(None, model_axis)),
        (r"head/bias$", P(model_axis)),
        (r"Dense_0/kernel$", P(None, model_axis)),
        (r"Dense_0/bias$", P(model_axis)),
        (r"Dense_1/kernel$", P(model_axis, None)),
        (r"(gate|up)/kernel$", P(None, model_axis)),
        (r"down/kernel$", P(model_axis, None)),
    ]
    return rules


def vit_tp_rules_table(model_axis: str = mesh_lib.MODEL_AXIS) -> list:
    """``tp.vit_tp_rules`` as data: the encoder-block Megatron pattern
    (ViT MLPs live under MlpBlock; patch embed / norms / head
    replicate via the fallback)."""
    return [
        (r"qkv/kernel$", P(None, None, model_axis, None)),
        (r"qkv/bias$", P(None, model_axis, None)),
        (r"out/kernel$", P(model_axis, None, None)),
        (r"MlpBlock.*Dense_0/kernel$", P(None, model_axis)),
        (r"MlpBlock.*Dense_0/bias$", P(model_axis)),
        (r"MlpBlock.*Dense_1/kernel$", P(model_axis, None)),
    ]


@dataclasses.dataclass(frozen=True)
class RuleTable:
    """A committed, named rule table plus the probe models FDT108
    checks it against (each probe: ``() -> (params_shapes, note)``
    where ``params_shapes`` is an eval_shape'd param tree — building a
    probe allocates nothing)."""

    name: str
    build: Callable[[], list]
    probes: Tuple[Callable[[], Tuple[Any, str]], ...]
    #: tables that intentionally match nothing (dp) or catch-all
    #: (fsdp) skip the large-unmatched check — replication/sharding of
    #: every leaf is their DOCUMENTED semantics, not a silent fallback
    check_unmatched: bool = True


def _probe_params(model, sample_shape, dtype="float32"):
    """eval_shape the model's init — param SHAPES without allocating
    a single buffer (rule matching and FDT108 only need paths and
    shapes)."""
    import jax
    import jax.numpy as jnp

    sample = jax.ShapeDtypeStruct(sample_shape, jnp.dtype(dtype))
    variables = jax.eval_shape(
        lambda s: model.init(jax.random.PRNGKey(0), s, train=False),
        sample)
    return variables["params"]


def _lm_probe(gqa: bool = False, swiglu: bool = False,
              tied: bool = True):
    def build():
        from ..models.transformer_lm import TransformerLM

        model = TransformerLM(
            vocab=32, dim=16, depth=2, num_heads=4, mlp_dim=32,
            num_kv_heads=2 if gqa else None,
            mlp="swiglu" if swiglu else "gelu",
            tie_embeddings=tied)
        note = (f"TransformerLM(gqa={gqa}, swiglu={swiglu}, "
                f"tied={tied})")
        return _probe_params(model, (1, 8), "int32"), note

    return build


def _vit_probe():
    from ..models.vit import ViT

    model = ViT(patch=4, depth=2, dim=16, num_heads=4, mlp_dim=32,
                num_classes=4)
    return _probe_params(model, (1, 8, 8, 3)), "ViT(tiny)"


def _cnn_probe():
    from ..models.simple import SimpleCNN

    model = SimpleCNN(num_classes=4, features=8)
    return _probe_params(model, (1, 8, 8, 3)), "SimpleCNN(tiny)"


#: name → committed table.  FDT108 sweeps every entry: a pattern that
#: matches NO leaf on any probe is a dead rule; a probe leaf >=
#: FALLBACK_MIN_SIZE matched by nothing is a silent replication.
RULE_TABLES = {
    "dp": RuleTable(
        "dp", dp_rules,
        probes=(_lm_probe(), _vit_probe, _cnn_probe),
        check_unmatched=False),
    "fsdp": RuleTable(
        "fsdp", fsdp_rules,
        probes=(_lm_probe(), _vit_probe, _cnn_probe),
        check_unmatched=False),
    "lm_tp": RuleTable(
        "lm_tp", lm_tp_rules_table,
        probes=(_lm_probe(), _lm_probe(gqa=True),
                _lm_probe(swiglu=True), _lm_probe(tied=False))),
    "vit_tp": RuleTable(
        "vit_tp", vit_tp_rules_table, probes=(_vit_probe,)),
}


def registered_rule_tables() -> dict:
    return dict(RULE_TABLES)


def rules_for_model(model, tp: bool = True) -> list:
    """The committed table for a model family: transformer LM / ViT
    get their Megatron tables (``tp=False`` — a layout with no model
    axis — drops to the empty table so the fsdp overlay alone decides
    placement); everything else (conv stacks, torch imports of them)
    uses the empty table + overlay, which is exactly what makes a new
    model shardable with NO spec code."""
    from ..models.transformer_lm import TransformerLM
    from ..models.vit import ViT

    if not tp:
        return dp_rules()
    if isinstance(model, TransformerLM):
        return lm_tp_rules_table()
    if isinstance(model, ViT):
        return vit_tp_rules_table()
    return dp_rules()
