"""Observability wired end-to-end (the ISSUE 3 acceptance surface).

Fast tier: a CPU training smoke run with ``Observation.full`` must land
the step counter, per-phase histograms, compile count and OOM-skip
counter in the process registry, export a valid Chrome trace-event
file, and expose it all over the stdlib ``/metrics`` endpoint.

Slow tier: ``bin/driver.py`` with the obs flags end-to-end, and the
trainer ``profile_dir`` → ``benchmarks/trace_analysis.py`` handoff
(captures a real profiler trace — too heavy for the fast loop).
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys

import pytest

from fluxdistributed_tpu import mesh as mesh_lib, optim
from fluxdistributed_tpu.data import SyntheticDataset
from fluxdistributed_tpu.models import SimpleCNN
from fluxdistributed_tpu.obs import Observation, get_registry
from fluxdistributed_tpu.train import NullLogger, prepare_training, train

REPO = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def mesh():
    return mesh_lib.data_mesh(8)


def _task(mesh, cycles=6):
    ds = SyntheticDataset(nsamples=64, nclasses=4, shape=(16, 16, 3))
    return prepare_training(
        SimpleCNN(num_classes=4), ds, optim.momentum(0.05, 0.9),
        mesh=mesh, batch_size=16, cycles=cycles,
    )


def test_train_smoke_populates_registry_and_trace(mesh, tmp_path):
    reg = get_registry()
    trace_path = str(tmp_path / "run.trace.json")
    profile_path = str(tmp_path / "run.profile.json")
    obs = Observation.full(trace_path=trace_path,
                           jsonl_path=str(tmp_path / "run.jsonl"),
                           profile_path=profile_path)
    steps_before = reg.value("fdtpu_train_steps_total")
    stalls_before = reg.value("fdtpu_watchdog_stalls_total")

    train(_task(mesh), print_every=2, eval_every=3, logger=NullLogger(),
          observation=obs)

    # step counter + per-phase histograms + compile count + OOM skips —
    # the acceptance criterion's /metrics payload
    assert reg.value("fdtpu_train_steps_total") == steps_before + 6
    hist = reg.get("fdtpu_train_phase_seconds")
    for phase in ("data_wait", "dispatch", "device", "eval"):
        assert hist.labels(phase=phase).count > 0, phase
    assert reg.value("fdtpu_jax_compiles_total") > 0
    assert reg.value("fdtpu_train_oom_skipped_total") >= 0
    # the loader reported its side of the pipeline
    assert reg.value("fdtpu_data_batches_total") > 0
    assert reg.get("fdtpu_data_h2d_seconds").cell_count() > 0
    # a steady 6-cycle run must not trip the watchdog
    assert reg.value("fdtpu_watchdog_stalls_total") == stalls_before

    # the span file is valid Chrome trace-event JSON with the step phases
    doc = json.loads(pathlib.Path(trace_path).read_text())
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"data_wait", "dispatch", "device", "h2d"} <= names
    for e in doc["traceEvents"]:
        assert e["ph"] == "X" and "ts" in e and "dur" in e

    # the jsonl sink appended print-cadence + final snapshots
    lines = [json.loads(l)
             for l in (tmp_path / "run.jsonl").read_text().splitlines()]
    assert lines and lines[-1]["final"]
    assert lines[-1]["metrics"]["fdtpu_train_steps_total"] >= 6

    # the cost-profile artifact: versioned, topology-verified, with the
    # REAL step's static price and this run's measured phases inside
    from fluxdistributed_tpu.obs import Profile

    prof = Profile.load(profile_path).verify(mesh)
    assert prof.static["step"]["flops"] > 0
    assert prof.measured["phases"]["dispatch"]["count"] >= 6
    assert prof.meta["model"] == "SimpleCNN" and prof.meta["steps"] == 6
    # the v2 sections: exact state bytes, the step's memory_analysis
    # breakdown, and the compiled step's collective ledger (the GSPMD
    # dp step all-reduces its gradients over the 8-device data axis)
    assert prof.schema == "fdtpu-profile/v2"
    assert prof.memory["state"]["param_bytes"] > 0
    assert prof.memory["step"] is None or (
        prof.memory["step"]["peak_bytes"] > 0)
    hlo = {e["kind"] for e in prof.comms["step"].get("hlo", [])}
    assert "all_reduce" in hlo


def test_train_metrics_scrapeable_over_http(mesh):
    import urllib.request

    from fluxdistributed_tpu.obs import start_metrics_server

    train(_task(mesh, cycles=2), print_every=0, eval_every=0,
          logger=NullLogger())  # default Observation: metrics-only
    srv = start_metrics_server(host="127.0.0.1", port=0)
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as r:
            text = r.read().decode()
        for series in ("fdtpu_train_steps_total",
                       "fdtpu_train_phase_seconds_bucket",
                       "fdtpu_jax_compiles_total",
                       "fdtpu_train_oom_skipped_total",
                       "fdtpu_data_prefetch_depth"):
            assert series in text, f"{series} missing:\n{text[:2000]}"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=10) as r:
            assert json.loads(r.read())["ok"]
    finally:
        srv.stop()


def _driver_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.mark.slow
def test_driver_obs_flags_end_to_end(tmp_path):
    """--trace-events/--metrics-jsonl/--steady-after plus the cold-start
    flags (--compile-cache/--aot/--prewarm) and --strict-checks through
    the CLI: artifacts appear and the run completes (a real training
    run passes the armed NaN-debug + transfer-guard first steps)."""
    trace = tmp_path / "driver.trace.json"
    jsonl = tmp_path / "driver.jsonl"
    cache = tmp_path / "compile-cache"
    aot = tmp_path / "aot"
    out = subprocess.run(
        [sys.executable, os.path.join("bin", "driver.py"),
         "--model", "SimpleCNN", "--dataset", "synthetic",
         "--num-classes", "4", "--image-size", "16",
         "--batch-size", "16", "--cycles", "4",
         "--print-every", "1", "--eval-every", "0",
         "--trace-events", str(trace), "--metrics-jsonl", str(jsonl),
         "--steady-after", "3",
         "--compile-cache", str(cache), "--aot", str(aot), "--prewarm",
         "--strict-checks",
         "--platform", "cpu", "--local-devices", "8"],
        capture_output=True, text=True, timeout=600, env=_driver_env(),
        cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-3000:]
    assert "done: 4 steps" in out.stdout, out.stdout[-2000:]
    doc = json.loads(trace.read_text())
    assert {"data_wait", "dispatch", "device"} <= {
        e["name"] for e in doc["traceEvents"]}
    lines = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert lines[-1]["metrics"]["fdtpu_train_steps_total"] == 4
    # cold-start artifacts: a topology-namespaced populated cache dir
    # and one serialized train-step executable
    (ns,) = os.listdir(cache)
    assert os.listdir(cache / ns), "compile cache stayed empty"
    assert any(f.startswith("train_step-") for f in os.listdir(aot))
    # --prewarm declared its cost before step 0
    assert "warmup:" in out.stdout, out.stdout[-2000:]


@pytest.mark.slow
def test_driver_metrics_port_scrape_mid_run(tmp_path):
    """--metrics-port serves /metrics + /healthz DURING training: poll
    until the endpoint answers, scrape, then let the run finish."""
    import socket
    import time
    import urllib.request

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, os.path.join("bin", "driver.py"),
         "--model", "SimpleCNN", "--dataset", "synthetic",
         "--num-classes", "4", "--image-size", "16",
         "--batch-size", "16", "--cycles", "300",
         "--print-every", "0", "--eval-every", "0",
         "--metrics-port", str(port),
         "--platform", "cpu", "--local-devices", "8"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=_driver_env(), cwd=str(REPO),
    )
    text = None
    try:
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break  # finished before we scraped — fail below with logs
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics", timeout=2) as r:
                    text = r.read().decode()
                break
            except OSError:
                time.sleep(0.2)
        assert text is not None, (
            f"never scraped /metrics; rc={proc.poll()}\n"
            f"{proc.stderr.read()[-3000:] if proc.poll() is not None else ''}"
        )
        for series in ("fdtpu_train_phase_seconds_bucket",
                       "fdtpu_jax_compiles_total",
                       "fdtpu_train_oom_skipped_total"):
            assert series in text, f"{series} missing:\n{text[:2000]}"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
            assert json.loads(r.read())["ok"]
    finally:
        proc.terminate()
        proc.wait(timeout=30)


@pytest.mark.slow
def test_profile_dir_to_trace_analysis_handoff(mesh, tmp_path, capsys):
    """A trainer profile_dir capture goes straight through the bench
    analyzer (one analyzer for production and bench traces)."""
    sys.path.insert(0, str(REPO))
    from benchmarks.trace_analysis import analyze

    pdir = str(tmp_path / "prof")
    train(_task(mesh, cycles=4), print_every=0, eval_every=0,
          logger=NullLogger(), profile_dir=pdir, profile_start=1,
          profile_steps=2)
    analyze(pdir, top=5)
    out = capsys.readouterr().out
    assert "by op class:" in out
    assert "top 5 ops by total time:" in out
