"""FDT304 positive: a non-daemon worker thread nothing ever joins
(blocks interpreter exit), and callback-gauge registrations with no
close path to unregister them (pins the object on shared registries)."""
import threading


class Pump:
    def start(self):
        self._thread = threading.Thread(target=self._run)
        self._thread.start()

    def _run(self):
        pass


class Gauges:
    def __init__(self, registry):
        self.registry = registry
        registry.gauge("fdtpu_toy_depth", "toy").set_function(
            lambda: 0.0)
