"""Pallas flash-attention kernel vs the XLA reference.

Runs the REAL kernel under the Pallas interpreter on the CPU CI mesh
(same code path as TPU modulo Mosaic lowering), pinned to
``dot_product_attention`` the way the reference pins its DP machinery to
single-batch gradients (test/single_device.jl:42-62).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# tier-2 (slow): 27 Pallas interpret-mode kernel tests — the tier-1 iteration loop must fit the
# 870s verify window (ROADMAP); CI's slow job still runs this file
pytestmark = pytest.mark.slow

from fluxdistributed_tpu.ops.attention import dot_product_attention
from fluxdistributed_tpu.ops.pallas_attention import flash_attention


def _qkv(b=2, t=64, h=2, d=32, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal, 16, 16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_non_divisible_seq():
    q, k, v = _qkv(t=40)
    ref = dot_product_attention(q, k, v)
    out = flash_attention(q, k, v, False, 16, 16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_causal_decode_shape():
    """Tq != Tk causal must end-align (KV-cache decode), like the reference."""
    q, _, _ = _qkv(t=8)
    q = q[:, :1]  # single query step
    _, k, v = _qkv(t=8, seed=1)
    ref = dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, True, 8, 8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_fully_masked_row_is_zero_everywhere():
    """All implementations agree: no attendable position → output 0."""
    q, k, v = _qkv(t=8)
    mask = jnp.ones((8, 8), bool).at[3].set(False)[None, None]
    ref = dot_product_attention(q, k, v, mask=mask)
    assert np.abs(np.asarray(ref[:, 3])).max() == 0.0

    # Flash kernel path: causal with Tq > Tk leaves the leading rows with
    # no attendable key (end-aligned) — they must be exactly 0, not NaN.
    q8, _, _ = _qkv(t=8, seed=2)
    _, k4, v4 = _qkv(t=4, seed=3)
    out = flash_attention(q8, k4, v4, True, 4, 4)
    ref2 = dot_product_attention(q8, k4, v4, causal=True)
    assert np.abs(np.asarray(out[:, :4])).max() == 0.0
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref2), rtol=2e-5, atol=2e-5)


def test_flash_bf16():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    ref = dot_product_attention(q, k, v)
    out = flash_attention(q, k, v, False, 16, 16)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), rtol=2e-2, atol=2e-2
    )


def test_flash_grads_match_reference():
    q, k, v = _qkv(t=32)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, True, 8, 8) ** 2).sum()

    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_non_divisible(causal):
    """Pallas backward with padded Q and KV blocks (t % block != 0)."""
    q, k, v = _qkv(t=40)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal, 16, 16) ** 2).sum()

    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=causal) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_flash_grads_decode_aligned():
    """Tq != Tk causal backward (end-aligned, the KV-cache convention)."""
    q, _, _ = _qkv(t=8)
    q = q[:, :4]
    _, k, v = _qkv(t=8, seed=1)

    gf = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, True, 4, 4) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: (dot_product_attention(q, k, v, causal=True) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_flash_grads_fully_masked_rows_finite():
    """Causal Tq > Tk leaves rows with no attendable key: their output is
    0, so every grad must be exactly finite (0 for dq rows) — not NaN
    from exp(s - LSE) with a degenerate LSE."""
    q8, _, _ = _qkv(t=8, seed=2)
    _, k4, v4 = _qkv(t=4, seed=3)

    gf = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, True, 4, 4) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q8, k4, v4)
    gr = jax.grad(
        lambda q, k, v: (dot_product_attention(q, k, v, causal=True) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q8, k4, v4)
    for a, b in zip(gf, gr):
        assert np.isfinite(np.asarray(a)).all()
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)
    # the first 4 query rows attend nothing → dq exactly 0 there
    assert np.abs(np.asarray(gf[0][:, :4])).max() == 0.0


def test_flash_grads_padded_k_extreme_scores_finite():
    """Non-causal with padded KV blocks and strongly-repelling q/k: a
    row whose every real score is << 0 has LSE < -88, where
    exp(0 - LSE) overflows f32 — the padded K column must be re-masked
    in the backward or dQ picks up inf·0 = NaN."""
    q, k, v = _qkv(t=24)  # 24 % 16 != 0 → one padded KV block
    q = q.at[:, 0].set(q[:, 0] * 0 + 5.0)
    k = k * 0 - 5.0  # row-0 scores ≈ -5·5·D/sqrt(D) ≈ -141 → LSE < -88

    gf = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, False, 16, 16) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: (dot_product_attention(q, k, v) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    # |s| ~ 1e2 exaggerates f32 cancellation in exp(s - LSE); the point
    # here is finiteness plus agreement at a tolerance matching that
    for a, b in zip(gf, gr):
        assert np.isfinite(np.asarray(a)).all()
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_matches_repeated_kv(causal):
    """Grouped-query attention: the kernel maps each group of query
    heads onto its shared KV head via BlockSpec index maps (KV never
    repeated in HBM) — fwd and bwd must equal dense attention over
    explicitly repeated KV."""
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    B, T, Hq, Hkv, D = 2, 32, 8, 2, 16
    q = jax.random.normal(ks[0], (B, T, Hq, D))
    k = jax.random.normal(ks[1], (B, T, Hkv, D))
    v = jax.random.normal(ks[2], (B, T, Hkv, D))
    rep = lambda x: jnp.repeat(x, Hq // Hkv, axis=2)

    ref = dot_product_attention(q, rep(k), rep(v), causal=causal)
    out = flash_attention(q, k, v, causal, 8, 8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    gf = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, causal, 8, 8) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: (
            dot_product_attention(q, rep(k), rep(v), causal=causal) ** 2
        ).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)  # autodiff through the repeat sums each group for dk/dv
    for a, b in zip(gf, gr):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("window", [1, 5, 16, 40])
def test_flash_sliding_window_matches_reference(window):
    """Sliding-window attention (causal): parity with the windowed dense
    core at window sizes below/at/above the block size and full-T,
    fwd AND bwd; non-divisible T exercises the padded band."""
    q, k, v = _qkv(t=40)
    ref = dot_product_attention(q, k, v, causal=True, window=window)
    out = flash_attention(q, k, v, True, 16, 16, window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    gf = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, True, 16, 16, window) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: (
            dot_product_attention(q, k, v, causal=True, window=window) ** 2
        ).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_flash_window_with_gqa():
    """Window and grouped KV compose in one kernel invocation."""
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    B, T, Hq, Hkv, D = 2, 48, 4, 2, 16
    q = jax.random.normal(ks[0], (B, T, Hq, D))
    k = jax.random.normal(ks[1], (B, T, Hkv, D))
    v = jax.random.normal(ks[2], (B, T, Hkv, D))
    rep = lambda x: jnp.repeat(x, Hq // Hkv, axis=2)
    ref = dot_product_attention(q, rep(k), rep(v), causal=True, window=10)
    out = flash_attention(q, k, v, True, 16, 16, 10)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_window_requires_causal():
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, False, 16, 16, 8)


def test_flash_gqa_rejects_indivisible_heads():
    q, k, v = _qkv(h=3)
    with pytest.raises(ValueError, match="multiple of num KV heads"):
        flash_attention(q, k[:, :, :2], v[:, :, :2], False, 16, 16)


def test_flash_grads_bf16():
    q, k, v = _qkv(dtype=jnp.bfloat16)

    gf = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, False, 16, 16).astype(jnp.float32) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: (dot_product_attention(q, k, v).astype(jnp.float32) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gr):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=5e-2, atol=5e-2
        )


def test_flash_in_vit():
    """ViT wired with the Pallas kernel == ViT with XLA attention."""
    from functools import partial

    from fluxdistributed_tpu.models import vit_tiny

    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    m_ref = vit_tiny(num_classes=10, dtype=jnp.float32)
    variables = m_ref.init(jax.random.PRNGKey(0), x, train=False)
    m_flash = vit_tiny(
        num_classes=10, dtype=jnp.float32,
        attn_fn=partial(flash_attention, block_q=16, block_k=16),
    )
    a = m_ref.apply(variables, x, train=False)
    b = m_flash.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("window,sinks", [(8, 2), (12, 8), (16, 1)])
def test_flash_attention_sinks_match_reference(window, sinks):
    """StreamingLLM sinks: first `sinks` keys stay attendable outside
    the window; parity with the windowed+sinked dense core fwd AND bwd
    (T=48 ensures band, sink, and dead regions all exist)."""
    q, k, v = _qkv(t=48)
    ref = dot_product_attention(q, k, v, causal=True, window=window, sinks=sinks)
    out = flash_attention(q, k, v, True, 16, 16, window, sinks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    gf = jax.grad(
        lambda q, k, v: (
            flash_attention(q, k, v, True, 16, 16, window, sinks) ** 2
        ).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: (
            dot_product_attention(
                q, k, v, causal=True, window=window, sinks=sinks) ** 2
        ).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_sinks_require_window():
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="window"):
        flash_attention(q, k, v, True, 16, 16, None, 2)
    with pytest.raises(ValueError, match="window"):
        dot_product_attention(q, k, v, causal=True, sinks=2)
