#!/usr/bin/env python
"""N-replica serving router — the fault-tolerant front process over
``bin/serve.py --lm`` replicas (``fluxdistributed_tpu.serve.router``).

Front an existing fleet::

    python bin/router.py --replica http://127.0.0.1:8001 \
        --replica http://127.0.0.1:8002 --port 8100

Supervise one (spawn the replicas yourself, restartable)::

    python bin/router.py --spawn 2 --port 8100 \
        --replica-cmd "python bin/serve.py --lm --model lm_tiny \
                       --prewarm --aot-dir aot/ --port 0"

Requests to ``POST /v1/generate`` route to the least-loaded healthy
replica (queue-wait p50 truth off each replica's /metrics) and fail
over transparently when a replica dies before its first token; a
client ``X-Request-Id`` rides every hop.  ``GET /healthz`` /
``/metrics`` / ``/trace`` roll the fleet up (replica-labeled series,
stitched Perfetto timelines).

Zero-downtime redeploy of a supervised fleet (one replica at a time:
drain → SIGTERM → respawn off the AOT pool → wait healthy)::

    python bin/router.py --rolling-restart http://127.0.0.1:8100

``--smoke`` runs the self-contained 2-replica failover demo CI uses:
fake-engine replicas, one killed mid-burst by a deterministic fault
plan, zero failed requests asserted, breaker transitions checked, and
the stitched trace written out.
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import sys
import threading
import time


def _bootstrap() -> None:
    """Make the package importable when run as ``python bin/router.py``
    from a checkout (no install, no PYTHONPATH) — the bin/lint.py
    pattern."""
    try:
        import fluxdistributed_tpu  # noqa: F401
    except ImportError:
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))


_bootstrap()


def _replica_env() -> dict:
    """Env for spawned replica children: they must import the package
    from the same place this process did."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return {"PYTHONPATH": os.pathsep.join(
        x for x in (root, os.environ.get("PYTHONPATH")) if x)}


def build_parser():
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--replica", action="append", default=[],
                   metavar="URL", dest="replicas",
                   help="replica base url (repeatable): front an "
                        "existing fleet")
    p.add_argument("--spawn", type=int, default=0, metavar="N",
                   help="supervise N replica subprocesses spawned from "
                        "--replica-cmd (--port 0 appended; the bound "
                        "port is read from the child's "
                        "FDTPU_SERVE_PORT= line) — enables rolling "
                        "restarts")
    p.add_argument("--replica-cmd", default=None, metavar="CMD",
                   help="command line for --spawn replicas, e.g. "
                        "\"python bin/serve.py --lm --model lm_tiny "
                        "--prewarm --aot-dir aot/\"")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8100,
                   help="router port (0 = ephemeral, announced as "
                        "FDTPU_ROUTER_PORT=<n> on stdout)")
    p.add_argument("--probe-interval", type=float, default=0.5,
                   help="seconds between /healthz probe sweeps")
    p.add_argument("--probe-timeout", type=float, default=2.0)
    p.add_argument("--failure-threshold", type=int, default=3,
                   help="consecutive probe/dispatch failures that open "
                        "a replica's circuit breaker")
    p.add_argument("--breaker-cooldown", type=float, default=2.0,
                   help="seconds an open breaker waits before "
                        "half-opening for a trial request")
    p.add_argument("--dispatch-tries", type=int, default=3,
                   help="dispatch attempts per request (failover "
                        "budget, faults.with_retries semantics)")
    p.add_argument("--upstream-timeout", type=float, default=600.0,
                   help="socket timeout per upstream dispatch")
    p.add_argument("--metrics-stale-after", type=float, default=3.0,
                   help="seconds after which a replica's load scrape "
                        "is stale and dispatch falls back to "
                        "round-robin")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="per-replica in-flight drain bound during "
                        "--rolling-restart")
    p.add_argument("--fault-plan", default=None, metavar="JSON",
                   help="router-side deterministic fault injection "
                        "(sites serve.dispatch / serve.probe); JSON "
                        "object or @file")
    p.add_argument("--rolling-restart", default=None, metavar="ROUTER_URL",
                   help="client mode: ask the running router at "
                        "ROUTER_URL to rolling-restart its supervised "
                        "fleet, print the result, exit")
    p.add_argument("--smoke", action="store_true",
                   help="run the self-contained 2-replica failover "
                        "smoke (fake engines, deterministic mid-burst "
                        "kill, rolling restart) and exit nonzero on "
                        "any dropped request")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write the stitched fleet Perfetto trace here "
                        "(smoke mode)")
    return p


def make_router(args):
    """Build the Router (+ spawned SupervisedReplicas in --spawn mode).
    Returns ``(router, supervisors)``."""
    from fluxdistributed_tpu.serve.router import (Replica, Router,
                                                  SupervisedReplica)

    router = Router(
        probe_interval=args.probe_interval,
        probe_timeout=args.probe_timeout,
        failure_threshold=args.failure_threshold,
        breaker_cooldown=args.breaker_cooldown,
        metrics_stale_after=args.metrics_stale_after,
        dispatch_tries=args.dispatch_tries,
        upstream_timeout=args.upstream_timeout,
    )
    sups = []
    for i, url in enumerate(args.replicas):
        router.add_replica(Replica(name=f"r{i}", url=url))
    if args.spawn:
        if not args.replica_cmd:
            raise SystemExit("--spawn needs --replica-cmd")
        base = len(args.replicas)
        argv = shlex.split(args.replica_cmd)
        for i in range(args.spawn):
            name = f"r{base + i}"
            sup = SupervisedReplica(argv, name=name, env=_replica_env())
            url = sup.spawn()
            sups.append(sup)
            router.add_replica(Replica(name=name, url=url,
                                       restart=sup.restart))
    if not router.replicas:
        raise SystemExit("no replicas: pass --replica URL and/or --spawn N")
    return router, sups


def rolling_restart_client(url: str, drain_timeout: float) -> int:
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url.rstrip("/") + "/admin/rolling_restart",
        data=json.dumps({"drain_timeout": drain_timeout}).encode(),
        method="POST", headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=600) as r:
            body = json.loads(r.read())
    except urllib.error.HTTPError as e:
        print(e.read().decode(), file=sys.stderr)
        return 1
    print(json.dumps(body, indent=2))
    return 0


# ---------------------------------------------------------------------------
# smoke: the CI 2-replica failover demo
# ---------------------------------------------------------------------------


def run_smoke(args) -> int:
    """2 fake-engine replica subprocesses; replica r0 carries a fault
    plan that hard-kills it (``os._exit``) at scheduler tick 60 —
    mid-burst.  A 32-request concurrent burst through the router must
    complete with ZERO failures and byte-exact deterministic tokens
    (failed-over requests re-generate identically on the survivor);
    the dead replica's breaker must open, then recover through
    half-open once it is brought back; a rolling restart under light
    load must drop nothing.  The stitched /trace goes to --trace-out."""
    import urllib.request

    from fluxdistributed_tpu.serve.router import (Replica, Router,
                                                  SupervisedReplica,
                                                  wait_http_ready)
    from fluxdistributed_tpu.serve.testing import fake_tokens

    here = os.path.dirname(os.path.abspath(__file__))
    serve_py = os.path.join(here, "serve.py")
    env = _replica_env()
    kill_plan = json.dumps(
        {"fail": [{"site": "serve.tick", "at": 60, "action": "exit"}]})

    def replica_argv(extra):
        return ([sys.executable, serve_py, "--lm", "--fake-engine",
                 "--max-slots", "4", "--max-len", "256",
                 "--max-queue", "64", "--fake-step-delay", "0.005",
                 "--trace-requests", "/dev/null", "--port", "0"]
                + extra)

    sup0 = SupervisedReplica(replica_argv(["--fault-plan", kill_plan]),
                             name="r0", env=env)
    sup1 = SupervisedReplica(replica_argv([]), name="r1", env=env)
    url0, url1 = sup0.spawn(), sup1.spawn()
    wait_http_ready(url0 + "/healthz")
    wait_http_ready(url1 + "/healthz")

    router = Router(probe_interval=0.2, probe_timeout=2.0,
                    failure_threshold=2, breaker_cooldown=0.5,
                    dispatch_tries=4, upstream_timeout=60.0)
    rep0 = router.add_replica(Replica("r0", url0, restart=sup0.restart))
    router.add_replica(Replica("r1", url1, restart=sup1.restart))
    httpd = router.serve("127.0.0.1", 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{router.bound_port}"
    failures = []

    def post(i, results):
        prompt = [i % 7 + 1, i % 5 + 1, i % 3 + 1]
        body = json.dumps({"prompt_tokens": prompt,
                           "max_tokens": 24}).encode()
        req = urllib.request.Request(
            f"{base}/v1/generate", data=body, method="POST",
            headers={"Content-Type": "application/json",
                     "X-Request-Id": f"smoke-{i}"})
        try:
            with urllib.request.urlopen(req, timeout=120) as r:
                results[i] = (r.status, json.loads(r.read()))
        except Exception as e:  # noqa: BLE001 — tallied below
            results[i] = (None, f"{type(e).__name__}: {e}")

    def burst(n, tag):
        results = {}
        threads = [threading.Thread(target=post, args=(i, results))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, (code, body) in sorted(results.items()):
            if code != 200:
                failures.append(f"{tag} request {i}: {code} {body}")
                continue
            if body.get("request_id") != f"smoke-{i}":
                failures.append(
                    f"{tag} request {i}: X-Request-Id not preserved "
                    f"({body.get('request_id')!r})")
            prompt = [i % 7 + 1, i % 5 + 1, i % 3 + 1]
            want = fake_tokens(prompt, 24)
            if body.get("generated") != want:
                failures.append(
                    f"{tag} request {i}: tokens diverged after "
                    f"failover: {body.get('generated')} != {want}")
        return results

    print("smoke: mid-burst kill (r0 exits at tick 60)...")
    burst(32, "kill-burst")
    deadline = time.monotonic() + 10
    while sup0.alive() and time.monotonic() < deadline:
        time.sleep(0.05)
    if sup0.alive():
        failures.append("fault plan did not kill r0")
    router.probe_now()
    opens = router.registry.value(
        "fdtpu_router_breaker_opens_total", "r0")
    if opens < 1:
        failures.append(f"breaker for r0 never opened (opens={opens})")

    print("smoke: r0 returns at its old port; breaker must recover...")
    old_port = sup0.port
    sup0.stop()  # reap the dead child
    sup0.argv = replica_argv([])  # successor WITHOUT the kill plan
    sup0.spawn(port=old_port)
    wait_http_ready(url0 + "/healthz")
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        router.probe_now()
        if rep0.breaker == "closed" and rep0.healthy:
            break
        time.sleep(0.1)
    if rep0.breaker != "closed":
        failures.append(
            f"breaker for r0 did not re-close (state={rep0.breaker})")

    print("smoke: rolling restart under light load...")
    stop_load = threading.Event()
    load_results = {}

    def light_load():
        i = 1000
        while not stop_load.is_set():
            post(i, load_results)
            i += 1
            time.sleep(0.05)

    load_thread = threading.Thread(target=light_load, daemon=True)
    load_thread.start()
    try:
        restarted = router.rolling_restart(drain_timeout=20.0,
                                           ready_timeout=60.0)
    finally:
        stop_load.set()
        load_thread.join(timeout=10)
    for i, (code, body) in sorted(load_results.items()):
        if code != 200:
            failures.append(
                f"rolling-restart load request {i}: {code} {body}")
    if len(restarted) != 2:
        failures.append(f"rolling restart covered {len(restarted)}/2")

    burst(8, "post-restart")
    doc = router.trace_document()
    pids = {e.get("pid") for e in doc["traceEvents"]}
    if len(pids) < 2:
        failures.append(f"stitched trace has {len(pids)} replica rows")
    if args.trace_out:
        with open(args.trace_out, "w") as f:
            json.dump(doc, f)
        print(f"stitched trace ({len(doc['traceEvents'])} events, "
              f"{len(pids)} replica rows) written to {args.trace_out}")

    httpd.shutdown()
    router.close()
    for sup in (sup0, sup1):
        sup.stop()
    if failures:
        print("SMOKE FAILED:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("smoke OK: 40 routed requests, 0 failures, breaker opened "
          "on the kill and recovered, rolling restart dropped nothing")
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.fault_plan:
        from fluxdistributed_tpu import faults

        spec = args.fault_plan
        if spec.startswith("@"):
            with open(spec[1:]) as f:
                spec = f.read()
        faults.install_plan(faults.FaultPlan.from_spec(json.loads(spec)))
    if args.rolling_restart:
        return rolling_restart_client(args.rolling_restart,
                                      args.drain_timeout)
    if args.smoke:
        return run_smoke(args)
    router, sups = make_router(args)
    httpd = router.serve(args.host, args.port)
    print(f"FDTPU_ROUTER_PORT={router.bound_port}", flush=True)
    print(f"routing {len(router.replicas)} replicas on "
          f"http://{args.host}:{router.bound_port}/v1/generate "
          f"(ctrl-c to stop)", flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        router.close()
        for sup in sups:
            sup.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
