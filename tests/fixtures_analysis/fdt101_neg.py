"""FDT101 negative: branches that are static at trace time."""
from functools import partial

import jax


@jax.jit
def shape_branch(x):
    if x.shape[0] > 1:  # .shape is static metadata
        return x
    return x * 2


@partial(jax.jit, static_argnums=(1,))
def static_branch(x, upscale):
    if upscale:  # declared static — ordinary Python bool
        return x * 2
    return x


@jax.jit
def none_branch(x, y):
    if y is None:  # identity test, not a value read
        return x
    return x + y


def host_helper(cfg):
    # not jit-reachable: plain host code branches freely
    if cfg:
        return 1
    return 0
