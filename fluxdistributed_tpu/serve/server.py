"""HTTP front end for the continuous-batching engine.

Stdlib-only (the bin/serve.py webcam-demo pattern, scaled to LM
serving): a ``ThreadingHTTPServer`` accepts requests on many threads,
every generation is enqueued onto ONE scheduler loop thread, and
streaming responses ride chunked transfer encoding.

Routes:

* ``POST /v1/generate`` — JSON body::

      {"prompt": "text"            # byte-level (vocab >= 256), OR
       "prompt_tokens": [1, 2],    # explicit token ids
       "max_tokens": 64,           # new tokens to generate
       "temperature": 0.0,         # 0 = greedy (parity with generate())
       "seed": 0, "eos": null,     # optional sampling seed / stop token
       "stream": false}            # chunked per-token streaming

  Non-streaming responses carry ``tokens`` (prompt+generated),
  ``generated``, decoded ``text`` for byte-level vocabs, and per-request
  timings.  Streaming responses emit one JSON line per token and a final
  ``{"done": true, ...}`` line.  A full admission queue returns **429**
  (backpressure), bad shapes return 400 with the engine's actionable
  message.
* ``GET /healthz`` — liveness + slot/queue occupancy.
* ``GET /metrics`` — Prometheus text: queue depth, active slots,
  prefill/decode tokens-per-sec, time-to-first-token + queue-wait +
  inter-token (TBT) histograms, compile counts.
* ``GET /trace`` — the request-scoped Perfetto timeline
  (``obs.reqtrace``; 404 when the scheduler has no tracer attached).

Request ids: a client ``X-Request-Id`` header becomes the request's
trace id — every reqtrace event and the response's ``request_id`` field
carry it, so a router can stitch its own logs to this replica's
timeline.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from .scheduler import Draining, QueueFull, Request, Scheduler

__all__ = ["LMServer", "serve_lm"]


class LMServer:
    """Scheduler loop thread + HTTP handler factory."""

    def __init__(self, scheduler: Scheduler, vocab: int,
                 request_timeout: float = 600.0):
        self.scheduler = scheduler
        self.vocab = vocab
        self.request_timeout = request_timeout
        #: the port :meth:`serve` actually bound (``--port 0`` gives an
        #: ephemeral one); surfaced on /healthz so a router or test
        #: orchestrating a fleet can discover it race-free
        self.bound_port: Optional[int] = None
        self._stop = threading.Event()
        self._loop_thread: Optional[threading.Thread] = None
        self.loop_errors = 0
        self.last_loop_error: Optional[str] = None
        # surfaced on /metrics too: a dead engine loop behind a healthy
        # HTTP listener is the failure mode /healthz exists for
        self.scheduler.registry.gauge(
            "fdtpu_serve_loop_errors",
            "engine-loop exceptions survived (nonzero = check logs)",
        ).set_function(lambda: self.loop_errors)

    def _memory_block(self) -> dict:
        """The /healthz memory payload: per-device HBM stats plus the
        KV cache's reserved/live bytes; ``{"available": false}`` (with
        the KV figures when the engine reports them) on backends
        without memory stats.  Never raises — a broken telemetry read
        must not take down the health endpoint."""
        try:
            from ..obs.memstats import hbm_summary

            out = hbm_summary()
            kb = getattr(self.scheduler.engine, "kv_cache_bytes", None)
            if callable(kb):
                out["kv_cache"] = kb()
            return out
        except Exception:  # noqa: BLE001
            return {"available": False}

    # ---- engine loop ------------------------------------------------------

    def start_loop(self) -> None:
        if self._loop_thread is not None:
            return
        self._loop_thread = threading.Thread(
            target=self._loop, name="lm-engine-loop", daemon=True)
        self._loop_thread.start()

    def stop_loop(self) -> None:
        self._stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=10)
            self._loop_thread = None
        self._stop.clear()

    # ---- graceful drain ---------------------------------------------------

    @property
    def draining(self) -> bool:
        return self.scheduler.draining

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful shutdown, SIGTERM-shaped: stop admissions (new
        submits get 503), let everything already accepted finish —
        bounded by ``timeout`` seconds — then stop the engine loop.
        ``/healthz`` reports 503 with ``"draining": true`` for the
        whole window, so a load balancer pulls this replica while
        in-flight decodes complete.

        Returns True when the drain finished clean (scheduler idle);
        False when the timeout cut it short — undone requests' clients
        see their own request timeouts, not silent token loss.
        """
        self.scheduler.begin_drain()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.scheduler.idle:
                break
            time.sleep(0.02)
        drained = self.scheduler.idle
        self.stop_loop()
        return drained

    def install_drain_handler(self, httpd=None, timeout: float = 30.0,
                              signals=None):
        """Install SIGTERM (and optionally more) handlers that run
        :meth:`drain` on a background thread — a signal handler must
        return immediately — and then ``shutdown()`` the HTTP server so
        ``serve_forever`` returns and the process exits 0.  Returns the
        :class:`~..faults.SignalFlag`-style uninstaller (callable) so
        tests can restore previous handlers."""
        import signal as _signal

        signals = tuple(signals) if signals is not None else (
            _signal.SIGTERM,)
        previous = {}

        def _drain_then_shutdown():
            self.drain(timeout)
            if httpd is not None:
                httpd.shutdown()

        def handler(signum, frame):
            threading.Thread(
                target=_drain_then_shutdown, name="lm-drain",
                daemon=True).start()

        for s in signals:
            previous[s] = _signal.signal(s, handler)

        def uninstall():
            for s, old in previous.items():
                try:
                    _signal.signal(s, old)
                except (ValueError, OSError):
                    pass

        return uninstall

    def close(self) -> None:
        """Full teardown: stop the engine loop and detach this server's
        (and its scheduler's) scrape callbacks from the registry — the
        shared-registry retirement path (see ``Scheduler.close``)."""
        self.stop_loop()
        self.scheduler.registry.unregister("fdtpu_serve_loop_errors")
        self.scheduler.close()

    def _loop(self) -> None:
        import sys
        import traceback

        sched = self.scheduler
        while not self._stop.is_set():
            try:
                if sched.idle:
                    sched.wait_for_work(0.05)
                    continue
                sched.step()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                # a dead loop with a healthy-looking server is a silent
                # permanent outage: log, count (surfaced by /healthz and
                # /metrics), back off a beat, keep serving
                self.loop_errors += 1
                self.last_loop_error = f"{type(e).__name__}: {e}"
                traceback.print_exc(file=sys.stderr)
                self._stop.wait(0.1)

    # ---- helpers ----------------------------------------------------------

    def _decode_text(self, toks) -> Optional[str]:
        if self.vocab != 256:
            return None
        from ..data import ByteTextDataset

        return ByteTextDataset.decode(toks)

    def _parse_request(self, body: dict) -> Request:
        if "prompt" in body and "prompt_tokens" in body:
            raise ValueError("pass prompt OR prompt_tokens, not both")
        if "prompt" in body:
            if self.vocab < 256:
                raise ValueError(
                    "text prompts are byte-encoded and need vocab >= 256; "
                    "this model has vocab "
                    f"{self.vocab} — pass prompt_tokens instead")
            prompt = list(str(body["prompt"]).encode("utf-8"))
        elif "prompt_tokens" in body:
            prompt = [int(t) for t in body["prompt_tokens"]]
            if prompt and (min(prompt) < 0 or max(prompt) >= self.vocab):
                raise ValueError(
                    f"prompt tokens must be in [0, {self.vocab})")
        else:
            raise ValueError("body needs prompt or prompt_tokens")
        temperature = float(body.get("temperature", 0.0))
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        eos = body.get("eos")
        return Request(
            prompt=prompt,
            max_new_tokens=int(body.get("max_tokens", 64)),
            temperature=temperature,
            seed=int(body.get("seed", 0)),
            eos_id=None if eos is None else int(eos),
        )

    def metrics_text(self) -> str:
        """Prometheus text exposition — rendered by the scheduler's
        shared metrics registry (``obs.metrics``).  Every pre-registry
        series name (``fdtpu_serve_*``) is preserved; the registry adds
        HELP/TYPE comment lines and histogram series."""
        return self.scheduler.registry.prometheus_text()

    # ---- HTTP -------------------------------------------------------------

    def make_handler(self):
        import http.server

        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code, obj):
                self._send(code, json.dumps(obj).encode(), "application/json")

            def do_GET(self):
                if self.path == "/healthz":
                    sched = outer.scheduler
                    loop = outer._loop_thread
                    alive = loop is not None and loop.is_alive()
                    draining = sched.draining
                    body = {
                        # a draining replica is deliberately unhealthy:
                        # the load balancer must pull it while in-flight
                        # decodes finish
                        "ok": alive and not draining,
                        "draining": draining,
                        "active_slots": sched.active_slots,
                        "max_slots": sched.engine.max_slots,
                        "queue_depth": sched.queue_depth,
                        "loop_errors": outer.loop_errors,
                        # per-device HBM truth (obs.memstats), or
                        # {"available": false} on CPU — a router can
                        # see a replica running out of margin before
                        # it starts OOMing requests
                        "memory": outer._memory_block(),
                    }
                    if outer.bound_port is not None:
                        body["port"] = outer.bound_port
                    if outer.last_loop_error:
                        body["last_loop_error"] = outer.last_loop_error
                    self._send_json(
                        200 if (alive and not draining) else 503, body)
                elif self.path == "/metrics":
                    self._send(200, outer.metrics_text().encode(),
                               "text/plain; version=0.0.4")
                elif self.path == "/trace":
                    rt = outer.scheduler.reqtrace
                    if rt is None:
                        self._send_json(404, {
                            "error": "request tracing is not enabled — "
                                     "attach an obs.RequestTracer to the "
                                     "scheduler (bin/serve.py "
                                     "--trace-requests)"})
                    else:
                        self._send_json(200, rt.trace_document())
                else:
                    self._send_json(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/v1/generate":
                    self._send_json(404, {"error": "not found"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be a JSON object")
                    req = outer._parse_request(body)
                    rid = self.headers.get("X-Request-Id")
                    if rid:
                        # the caller's correlation id becomes the trace
                        # id every downstream event carries
                        req.rid = str(rid)[:128]
                except (ValueError, TypeError, json.JSONDecodeError) as e:
                    # TypeError covers type-malformed fields (e.g.
                    # prompt_tokens: 5) — still the client's 400, not a 500
                    self._send_json(400, {"error": str(e)})
                    return
                stream = bool(body.get("stream", False))
                if stream:
                    self._stream(req)
                else:
                    self._blocking(req)

            def _submit(self, req) -> bool:
                try:
                    outer.scheduler.submit(req)
                    return True
                except Draining as e:
                    # 503 (not 429): retrying this instance is
                    # pointless, route to another replica
                    self._send_json(503, {"error": str(e),
                                          "draining": True})
                except QueueFull as e:
                    self.send_response(429)
                    self.send_header("Retry-After", "1")
                    body = json.dumps({"error": str(e)}).encode()
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except ValueError as e:
                    self._send_json(400, {"error": str(e)})
                return False

            def _result(self, req) -> dict:
                out = {
                    "id": req.id,
                    "request_id": req.trace_id,
                    "tokens": req.tokens,
                    "generated": list(req.generated),
                }
                text = outer._decode_text(req.tokens)
                if text is not None:
                    out["text"] = text
                if req.admitted_at and req.submitted_at:
                    out["queue_wait_ms"] = round(
                        (req.admitted_at - req.submitted_at) * 1e3, 2)
                if req.first_token_at and req.submitted_at:
                    out["ttft_ms"] = round(
                        (req.first_token_at - req.submitted_at) * 1e3, 2)
                if req.finished_at and req.first_token_at:
                    dt = req.finished_at - req.first_token_at
                    if dt > 0 and len(req.generated) > 1:
                        out["decode_tokens_per_sec"] = round(
                            (len(req.generated) - 1) / dt, 2)
                        out["tbt_ms_avg"] = round(
                            dt / (len(req.generated) - 1) * 1e3, 2)
                return out

            def _blocking(self, req):
                if not self._submit(req):
                    return
                if not req.done.wait(outer.request_timeout):
                    self._send_json(504, {"error": "generation timed out"})
                    return
                self._send_json(200, self._result(req))

            def _stream(self, req):
                import queue as _q

                toks: _q.Queue = _q.Queue()
                req.on_token = lambda r, t: toks.put(t)
                if not self._submit(req):
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/jsonlines")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(obj):
                    data = (json.dumps(obj) + "\n").encode()
                    self.wfile.write(f"{len(data):x}\r\n".encode())
                    self.wfile.write(data + b"\r\n")
                    self.wfile.flush()

                try:
                    import time as _time

                    deadline = _time.monotonic() + outer.request_timeout
                    while _time.monotonic() < deadline:
                        try:
                            t = toks.get(timeout=0.05)
                        except _q.Empty:
                            # on_token fires BEFORE done is set; only a
                            # drained queue + done means truly finished
                            if req.done.is_set() and toks.empty():
                                break
                            continue
                        chunk({"token": int(t)})
                    if req.done.is_set():
                        chunk({"done": True, **self._result(req)})
                    else:
                        # deadline hit with the request still running:
                        # report the truncation (the blocking path's 504)
                        # instead of masquerading as a clean completion
                        chunk({"done": False,
                               "error": "generation timed out",
                               **self._result(req)})
                except (BrokenPipeError, ConnectionResetError):
                    # client went away mid-stream: cancel so the slot —
                    # and, on a paged engine, its KV blocks — frees on
                    # the next tick instead of decoding to max_tokens
                    # for nobody
                    outer.scheduler.cancel(req)
                finally:
                    try:
                        self.wfile.write(b"0\r\n\r\n")
                        self.wfile.flush()
                    except OSError:
                        pass

        return Handler

    def serve(self, host: str = "127.0.0.1", port: int = 8000):
        """Build the HTTP server (started loop included); caller runs
        ``serve_forever`` — the bin/serve.py pattern, so tests can drive
        the server in a thread."""
        import http.server

        self.start_loop()
        httpd = http.server.ThreadingHTTPServer((host, port),
                                                self.make_handler())
        self.bound_port = httpd.server_address[1]
        return httpd


def serve_lm(scheduler: Scheduler, vocab: int, host: str = "127.0.0.1",
             port: int = 8000, request_timeout: float = 600.0):
    """One-call wiring: ``(LMServer, ThreadingHTTPServer)``."""
    srv = LMServer(scheduler, vocab, request_timeout=request_timeout)
    return srv, srv.serve(host, port)
