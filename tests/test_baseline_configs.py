"""BASELINE.md "configs to exercise" smoke matrix.

Each of the five named configurations (BASELINE.json "configs": ResNet-34
/CIFAR-10, ResNet-50 task-DP, ResNet-152 multi-host-style DP, ViT-L/16,
ConvNeXt-XL LARS) runs at tiny scale through the REAL trainer path —
same model family, same optimizer family, same spmd mode — so a config
can't silently rot while its pieces stay individually green.  Scale is
the only substitution (8 fake devices, small images, few steps); every
code path a full run would touch is the one exercised here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# tier-2 (slow): five full trainer configs, minutes of XLA compiles — the tier-1 iteration loop must fit the
# 870s verify window (ROADMAP); CI's slow job still runs this file
pytestmark = pytest.mark.slow

from fluxdistributed_tpu import mesh as mesh_lib, optim
from fluxdistributed_tpu.data import SyntheticDataset
from fluxdistributed_tpu.models import (
    convnext_test, resnet34, resnet50, resnet152, vit_tiny,
)
from fluxdistributed_tpu.train import prepare_training, train
from fluxdistributed_tpu.train.logging import NullLogger

CONFIGS = {
    # BASELINE "ResNet-34/CIFAR-10 (CPU ref)": momentum DP
    "resnet34-cifar": dict(
        model=lambda: resnet34(num_classes=10, dtype=jnp.float32),
        opt=lambda: optim.momentum(0.05, 0.9), spmd="jit", shape=(24, 24, 3),
        nclasses=10,
    ),
    # BASELINE "ResNet-50 task-DP (v4-8)": the headline config
    "resnet50-dp": dict(
        model=lambda: resnet50(num_classes=8, dtype=jnp.float32),
        opt=lambda: optim.momentum(0.05, 0.9), spmd="jit", shape=(32, 32, 3),
        nclasses=8,
    ),
    # BASELINE "ResNet-152 multi-host (v4-32)": deepest family member;
    # multi-host DP is the same compiled program over a bigger mesh
    # (process-boundary crossing is covered by tests/test_multihost.py)
    "resnet152-dp": dict(
        model=lambda: resnet152(num_classes=4, dtype=jnp.float32),
        opt=lambda: optim.momentum(0.05, 0.9), spmd="jit", shape=(32, 32, 3),
        nclasses=4,
    ),
    # BASELINE "ViT-L/16 (v5e-64)": ViT family under adamw
    "vit-adamw": dict(
        model=lambda: vit_tiny(num_classes=6, dtype=jnp.float32, dropout=0.0),
        opt=lambda: optim.adamw(1e-3, weight_decay=0.05), spmd="jit",
        shape=(32, 32, 3), nclasses=6,
    ),
    # BASELINE "ConvNeXt-XL large-batch LARS (v5p-128)": ConvNeXt + LARS
    "convnext-lars": dict(
        model=lambda: convnext_test(num_classes=4, dtype=jnp.float32),
        opt=lambda: optim.lars(0.1), spmd="jit", shape=(32, 32, 3),
        nclasses=4,
    ),
}


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_baseline_config_trains(name):
    cfg = CONFIGS[name]
    mesh = mesh_lib.data_mesh(8)
    ds = SyntheticDataset(nsamples=64, nclasses=cfg["nclasses"], shape=cfg["shape"])
    task = prepare_training(
        cfg["model"](), ds, cfg["opt"](), mesh=mesh, batch_size=16,
        cycles=3, topk=(1,), spmd=cfg["spmd"],
    )
    train(task, print_every=0, eval_every=0, topk=(1,), logger=NullLogger())
    assert int(task.state.step) == 3
    # every param leaf stayed finite through the config's optimizer
    for leaf in jax.tree.leaves(task.state.params):
        assert np.isfinite(np.asarray(leaf)).all()
