"""Toolchain compatibility shims.

The framework is written against the current JAX surface
(``jax.shard_map`` with the ``check_vma`` keyword, PEP 680 ``tomllib``).
Older toolchains — e.g. a Python 3.10 / jax 0.4.x image — carry the same
functionality under earlier names (``jax.experimental.shard_map`` with
``check_rep``, the ``tomli`` backport).  Importing this module (the
package ``__init__`` does, before anything touches jax) installs
forwarders so the rest of the codebase is written ONCE against the
modern names:

* ``jax.shard_map`` — forwarded to ``jax.experimental.shard_map`` when
  absent, translating ``check_vma=`` to the old ``check_rep=`` spelling.
* ``tomllib`` — aliased to ``tomli`` in ``sys.modules`` when the stdlib
  module is missing (Python < 3.11), so plain ``import tomllib`` works.
* :func:`configure_compilation_cache` — the persistent-compilation-
  cache config knobs (``jax_compilation_cache_dir`` et al.) under their
  several historical spellings; on a build with none of them the call
  warns and reports False instead of crashing, so cache enablement is
  always safe to leave on.
* :func:`compiled_memory_analysis` / :func:`device_memory_stats` — the
  memory-observability surface (``Compiled.memory_analysis()``,
  ``Device.memory_stats()``) normalized to plain dicts, returning None
  on builds/backends without it (CPU devices report no memory stats;
  some jax builds lack ``memory_analysis`` entirely).  Every consumer
  (obs.memstats, the HBM gauges, bin/fit.py) treats None as
  "unavailable", never an error.

No-ops on a modern toolchain.
"""

from __future__ import annotations

import sys

import jax

# True when this process runs the pre-VMA shard_map (jax <= 0.4.x).  The
# legacy tracer does NOT insert the psum that the modern varying-manual-
# axes transpose adds when differentiating w.r.t. a replicated input
# inside shard_map — code relying on that implicit gradient reduction
# (dp.make_train_step_shardmap) must branch on this flag and reduce
# explicitly.
LEGACY_SHARD_MAP = not hasattr(jax, "shard_map")


def _install_shard_map() -> None:
    if hasattr(jax, "shard_map"):
        return
    import inspect

    from jax.experimental.shard_map import shard_map as _legacy

    legacy_params = inspect.signature(_legacy).parameters

    def shard_map(f=None, /, **kwargs):
        if f is None:  # used as @partial(jax.shard_map, mesh=..., ...)
            # keep kwargs untranslated in the curried form: translation
            # must run exactly once, at the final call, or an explicit
            # check_vma=True would be clobbered by the re-entry default
            import functools

            return functools.partial(shard_map, **kwargs)
        if "check_vma" not in legacy_params:
            # the legacy replication checker predates the modern varying-
            # manual-axes inference and rejects valid programs (e.g. the
            # psum implicit in differentiating w.r.t. replicated params),
            # so it is only enabled on explicit request
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            else:
                kwargs.setdefault("check_rep", False)
        return _legacy(f, **kwargs)

    jax.shard_map = shard_map


def _install_tree_paths() -> None:
    """jax.tree.{leaves,flatten,map}_with_path appeared after 0.4.37;
    forward them to the long-stable jax.tree_util spellings."""
    import jax.tree
    import jax.tree_util as tu

    for name, impl in (
        ("leaves_with_path", tu.tree_leaves_with_path),
        ("flatten_with_path", tu.tree_flatten_with_path),
        ("map_with_path", tu.tree_map_with_path),
    ):
        if not hasattr(jax.tree, name):
            setattr(jax.tree, name, impl)


def _install_vma_stubs() -> None:
    """``jax.typeof`` / ``jax.lax.pcast`` are the VMA-era typing surface
    (pipeline code uses them to mark values varying before ppermute).
    The legacy tracer has no replication typing — every value is
    effectively varying — so a no-op pcast and an aval-returning typeof
    (whose missing ``.vma`` attribute makes callers' ``getattr(...,
    frozenset())`` guards take the convert path harmlessly) are exactly
    faithful."""
    import jax.core
    from jax import lax

    if not hasattr(jax, "typeof"):
        jax.typeof = jax.core.get_aval
    if not hasattr(lax, "pcast"):
        lax.pcast = lambda x, axis_name, *, to: x


def _try_config_update(name: str, value) -> bool:
    """``jax.config.update`` that reports instead of raising on a knob
    this jax build does not define (the error type varies by version:
    AttributeError on modern builds, KeyError/ValueError historically)."""
    try:
        jax.config.update(name, value)
        return True
    except (AttributeError, KeyError, ValueError, TypeError):
        return False


def configure_compilation_cache(
    cache_dir: str,
    *,
    min_entry_size_bytes=None,
    min_compile_time_secs=None,
) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir``.

    Tries the config-option spelling first (``jax_compilation_cache_dir``
    — jax >= 0.4.x), then the ``compilation_cache.set_cache_dir`` API of
    older builds.  The threshold knobs
    (``jax_persistent_cache_min_entry_size_bytes`` /
    ``jax_persistent_cache_min_compile_time_secs``) are best-effort: a
    build without them keeps its defaults silently — they tune WHAT gets
    cached, not whether caching works.

    Returns True when a cache directory was installed by either path;
    False (after a one-line warning) when this jax has no persistent
    cache at all — callers treat that as "enablement is a no-op", never
    an error.
    """
    installed = _try_config_update("jax_compilation_cache_dir", cache_dir)
    if not installed:
        try:  # pre-config-option spelling
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.set_cache_dir(cache_dir)  # type: ignore[attr-defined]
            installed = True
        except Exception:  # noqa: BLE001 — absence, not failure
            installed = False
    if not installed:
        import warnings

        warnings.warn(
            "this jax build has no persistent compilation cache "
            "(jax_compilation_cache_dir / compilation_cache.set_cache_dir "
            "both absent); cold-start caching is disabled",
            RuntimeWarning,
            stacklevel=2,
        )
        return False
    if min_entry_size_bytes is not None:
        _try_config_update(
            "jax_persistent_cache_min_entry_size_bytes", min_entry_size_bytes)
    if min_compile_time_secs is not None:
        _try_config_update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_time_secs)
    # jax decides once per process whether the cache is usable and then
    # memoizes the answer; clear that memo so enabling the cache AFTER
    # an early compile (a REPL, a test that ran first) still takes
    # effect for every later compile
    try:
        from jax._src import compilation_cache as _icc

        _icc.reset_cache()
    except Exception:  # noqa: BLE001 — older layouts; memo just stays
        pass
    return True


#: CompiledMemoryStats fields we normalize, in the XLA spelling minus
#: the ``_size_in_bytes`` suffix.  ``peak`` is derived: the XLA
#: approximation of live HBM while the program runs is arguments +
#: outputs + temporaries minus the aliased (donated) overlap.
_MEMORY_FIELDS = ("generated_code", "argument", "output", "alias", "temp")


def compiled_memory_analysis(compiled) -> "dict | None":
    """``Compiled.memory_analysis()`` normalized to plain int bytes:
    ``{"generated_code_bytes", "argument_bytes", "output_bytes",
    "alias_bytes", "temp_bytes", "peak_bytes"}``.

    Returns None — never raises — when this jax build has no
    ``memory_analysis``, the backend reports none (some plugin runtimes
    return None), or the stats object lacks the expected fields.  A
    missing memory model must degrade the observability artifact, not
    kill the run producing it.
    """
    fn = getattr(compiled, "memory_analysis", None)
    if fn is None:
        return None
    try:
        st = fn()
    except Exception:  # noqa: BLE001 — absence/unsupported, not failure
        return None
    if st is None:
        return None
    out = {}
    for name in _MEMORY_FIELDS:
        v = getattr(st, f"{name}_size_in_bytes", None)
        if v is None and isinstance(st, dict):
            v = st.get(f"{name}_size_in_bytes")
        if v is None:
            return None
        out[f"{name}_bytes"] = int(v)
    out["peak_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                         + out["temp_bytes"] - out["alias_bytes"])
    return out


def device_memory_stats(device) -> "dict | None":
    """``Device.memory_stats()`` as a plain dict, or None when the
    device does not report memory (CPU devices return None; older
    plugin backends lack the method).  Never raises."""
    fn = getattr(device, "memory_stats", None)
    if fn is None:
        return None
    try:
        st = fn()
    except Exception:  # noqa: BLE001 — absence/unsupported, not failure
        return None
    if not st:
        return None
    return dict(st)


def _install_tomllib() -> None:
    if "tomllib" in sys.modules:
        return
    try:
        import tomllib  # noqa: F401 — stdlib (3.11+): nothing to do
    except ModuleNotFoundError:
        try:
            import tomli
        except ModuleNotFoundError:
            return  # registry.load_registry will raise its own ImportError
        sys.modules["tomllib"] = tomli


_install_shard_map()
_install_tree_paths()
_install_vma_stubs()
_install_tomllib()
