"""Rematerialization (jax.checkpoint) parity across model families.

``remat=True`` must be a pure memory/FLOPs trade: loss, gradients, and
mutable state bit-match the non-remat model, and parameter paths are
unchanged (the remat wrapper must not rename flax scopes — that would
orphan checkpoints and imported torch weights).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# tier-2 (slow): bit-level grad parity across remat'd full models — the tier-1 iteration loop must fit the
# 870s verify window (ROADMAP); CI's slow job still runs this file
pytestmark = pytest.mark.slow

import fluxdistributed_tpu as fd
from fluxdistributed_tpu.models import convnext_test, lm_tiny, resnet18, vit_tiny
from fluxdistributed_tpu.models import lm_loss_fn
from fluxdistributed_tpu.parallel.dp import flax_loss_fn


def _grad_parity(m0, mr, loss_of, params):
    (l0, aux0), g0 = jax.value_and_grad(lambda p: loss_of(m0, p), has_aux=True)(params)
    (l1, aux1), g1 = jax.value_and_grad(lambda p: loss_of(mr, p), has_aux=True)(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for pa, a, b in zip(
        [k for k, _ in jax.tree_util.tree_leaves_with_path(g0)],
        jax.tree.leaves(g0),
        jax.tree.leaves(g1),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(pa)}",
        )
    return aux0, aux1


@pytest.mark.parametrize("family", ["resnet", "vit", "convnext"])
def test_image_model_remat_parity(family):
    mk = {
        "resnet": lambda **kw: resnet18(num_classes=10, dtype=jnp.float32, **kw),
        "vit": lambda **kw: vit_tiny(num_classes=10, dtype=jnp.float32, **kw),
        "convnext": lambda **kw: convnext_test(num_classes=10, dtype=jnp.float32, **kw),
    }[family]
    m0, mr = mk(), mk(remat=True)
    x = np.random.default_rng(0).normal(0, 1, (4, 32, 32, 3)).astype(np.float32)
    y = np.asarray(fd.onehot(np.arange(4) % 10, 10))
    variables = m0.init(jax.random.PRNGKey(0), x[:1], train=True)
    params = variables["params"]
    mstate = {k: v for k, v in variables.items() if k != "params"}

    # identical param paths: remat must not rename scopes
    vr = mr.init(jax.random.PRNGKey(0), x[:1], train=True)
    assert jax.tree_util.tree_structure(variables["params"]) == \
        jax.tree_util.tree_structure(vr["params"])

    def loss_of(model, p):
        loss, (ms, _) = flax_loss_fn(model, fd.logitcrossentropy)(
            p, mstate, {"image": x, "label": y}, True
        )
        return loss, ms

    ms0, ms1 = _grad_parity(m0, mr, loss_of, params)
    for a, b in zip(jax.tree.leaves(ms0), jax.tree.leaves(ms1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_lm_remat_parity():
    m0 = lm_tiny(vocab=32, dtype=jnp.float32)
    mr = lm_tiny(vocab=32, dtype=jnp.float32, remat=True)
    toks = np.random.default_rng(1).integers(0, 32, (4, 16)).astype(np.int32)
    params = m0.init(jax.random.PRNGKey(0), toks, train=False)["params"]

    def loss_of(model, p):
        loss, (ms, _) = lm_loss_fn(model)(p, {}, {"tokens": toks}, True)
        return loss, ms

    _grad_parity(m0, mr, loss_of, params)


def test_lm_remat_decode_unaffected():
    """decode=True ignores remat (no backward pass at inference; the
    cache write must not go through a checkpoint boundary)."""
    from fluxdistributed_tpu.models import generate

    mr = lm_tiny(vocab=32, dtype=jnp.float32, decode=True, remat=True)
    m0 = lm_tiny(vocab=32, dtype=jnp.float32, decode=True)
    toks = np.asarray([[3, 7]], np.int32)
    params = lm_tiny(vocab=32, dtype=jnp.float32).init(
        jax.random.PRNGKey(0), toks, train=False
    )["params"]
    out_r = np.asarray(generate(mr, params, toks, total_len=6))
    out_0 = np.asarray(generate(m0, params, toks, total_len=6))
    np.testing.assert_array_equal(out_r, out_0)
