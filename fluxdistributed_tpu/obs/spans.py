"""Nested step-phase spans with Chrome/Perfetto trace-event export.

``jax.profiler`` answers "what did the DEVICE do" at ~GB trace cost for
a fixed window; this tracer answers "where did the HOST loop's time go"
continuously and for pennies: the trainer brackets every phase of every
step (data-wait / h2d / dispatch / device / eval / checkpoint) in a
span, spans nest through a contextvar (so helper code can add spans
without threading a handle), and the buffer exports as Chrome
trace-event JSON — load it at ``chrome://tracing`` or ui.perfetto.dev
next to a device trace.

Overhead discipline:

* a **disabled** tracer hands out one shared no-op context manager —
  the instrumented hot loop pays an attribute load and a truthiness
  check, no allocation;
* an **enabled** tracer appends one small dict per span to a bounded
  ring (default 200k events ≈ a few hours of stepping) under a lock
  only at span END; timestamps come from ``perf_counter`` (monotonic,
  ns resolution).

Spans can simultaneously feed a registry :class:`~.metrics.Histogram`
labeled by phase, so the SAME brackets produce both the live
``/metrics`` percentiles and the offline timeline.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import threading
import time
from collections import deque
from typing import Optional

from .metrics import Histogram

__all__ = ["SpanTracer", "current_span", "innermost_active", "phase_scope"]

# name of the innermost open span in this context ("" at top level);
# contextvars give correct nesting across threads AND async contexts
_stack: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "fdtpu_span_stack", default=()
)


def current_span() -> Optional[str]:
    """Innermost open span name in the calling context, or ``None``."""
    s = _stack.get()
    return s[-1] if s else None


# -- cross-thread active-span registry -------------------------------------
# The contextvar above answers "where am I" for the CALLING context; the
# stall watchdog needs "where is the LOOP" from its own daemon thread.
# Every open span (and every tracer-less phase bracket via phase_scope)
# also registers here: {thread_id: [(seq, name), ...]}, where seq is a
# global open-order counter so "innermost" is well-defined across
# threads.  One small lock + list op per span — phases tick a handful of
# times per step, never per token.
_active_lock = threading.Lock()
_active: dict = {}
_active_seq = itertools.count(1)


def _active_push(name: str) -> None:
    tid = threading.get_ident()
    with _active_lock:
        _active.setdefault(tid, []).append((next(_active_seq), name))


def _active_pop() -> None:
    tid = threading.get_ident()
    with _active_lock:
        stack = _active.get(tid)
        if stack:
            stack.pop()
        if not stack:
            _active.pop(tid, None)


def innermost_active() -> Optional[str]:
    """Name of the most recently OPENED still-open span/phase across all
    threads, or ``None`` — what the stall watchdog reports as "where the
    loop is wedged" (a stalled step is, by definition, inside whichever
    bracket opened last and never closed)."""
    with _active_lock:
        newest, name = 0, None
        for stack in _active.values():
            if stack and stack[-1][0] > newest:
                newest, name = stack[-1]
    return name


@contextlib.contextmanager
def phase_scope(name: str):
    """Register ``name`` as the active phase WITHOUT a tracer: the
    metrics-only trainer path brackets its phases with this so the
    watchdog can still name where a stall happened (no event buffer, no
    histogram — just the active-span registry above)."""
    _active_push(name)
    try:
        yield
    finally:
        _active_pop()


class _NullSpan:
    """The disabled path — one shared instance, __enter__/__exit__ only."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "args", "_t0", "_token")

    def __init__(self, tracer: "SpanTracer", name: str, args: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        self._token = _stack.set(_stack.get() + (self.name,))
        _active_push(self.name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        _active_pop()
        _stack.reset(self._token)
        self._tracer._record(self.name, self._t0, t1, self.args)
        return False


class SpanTracer:
    """Collects spans; exports Chrome trace-event JSON.

    Parameters
    ----------
    enabled: hand out real spans (False = shared no-op, near-zero cost)
    max_events: ring capacity; oldest events drop first (a days-long run
        must not grow host memory without bound)
    histogram: optional labeled :class:`Histogram` — every completed
        span also observes its seconds under ``{label: name}`` so the
        same bracket feeds /metrics
    label: the histogram's label name (default ``"phase"``)
    """

    def __init__(
        self,
        enabled: bool = True,
        max_events: int = 200_000,
        histogram: Optional[Histogram] = None,
        label: str = "phase",
    ):
        self.enabled = enabled
        self.histogram = histogram
        self.label = label
        self._events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()
        # trace-event ts fields are µs relative to this origin; pairing
        # with wall time lets readers line the trace up with log stamps
        self._origin = time.perf_counter()
        self._origin_unix = time.time()
        self.dropped = 0

    def span(self, name: str, **args):
        """``with tracer.span("data_wait"):`` — bracket one phase."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args or None)

    def _record(self, name, t0, t1, args) -> None:
        if self.histogram is not None:
            self.histogram.labels(**{self.label: name}).observe(t1 - t0)
        ev = {
            "name": name,
            "ph": "X",  # complete event: begin ts + dur in one record
            "ts": (t0 - self._origin) * 1e6,
            "dur": (t1 - t0) * 1e6,
            "pid": 0,
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "cat": "fdtpu",
        }
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def trace_events(self) -> list:
        """The Chrome trace-event list (JSON-ready dicts, time-ordered
        per thread by construction)."""
        with self._lock:
            return list(self._events)

    def export_chrome_trace(self, path: str) -> int:
        """Write the buffer as a Chrome/Perfetto trace-event JSON file;
        returns the number of events written.

        The JSON Object Format (``{"traceEvents": [...]}``) is used
        rather than the bare array so metadata rides along; both load in
        chrome://tracing and Perfetto.
        """
        events = self.trace_events()
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "origin_unix_time": self._origin_unix,
                "dropped_events": self.dropped,
                "producer": "fluxdistributed_tpu.obs.spans",
            },
        }
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(events)
