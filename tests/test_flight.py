"""Flight recorder (obs/flight.py): the black box must survive the
crash it exists for.

Tier-1 here: ring/flush mechanics, torn-tail tolerance, footer
idempotence, trainer wiring, and a SIMULATED hard death (a subprocess
that records then ``os._exit``s — the no-finally shape of a SIGKILL,
without paying a driver launch).  The real-SIGKILL driver kill rides
the slow tier below; CI's ``supervise.py --crash-smoke`` exercises the
same path end-to-end."""

import os
import subprocess
import sys
import textwrap

import pytest

from fluxdistributed_tpu.obs.flight import (
    FLIGHT_SCHEMA,
    FlightRecorder,
    read_flight,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ring_and_flush_cadence(tmp_path):
    """Records flush every ``flush_every``; the gap between recorded
    and flushed — the maximum a SIGKILL can lose — never exceeds one
    interval."""
    p = str(tmp_path / "f.jsonl")
    fr = FlightRecorder(p, ring=6, flush_every=4)
    for i in range(10):
        fr.record(step=i)
        assert fr.recorded - fr.flushed < 4
    assert fr.recorded == 10
    assert fr.flushed == 8  # two cadence flushes; 2 pending
    # the ring keeps only the newest 6 in memory
    assert [r["step"] for r in fr.records()] == [4, 5, 6, 7, 8, 9]
    # on disk: header + the 8 flushed records, no footer yet
    out = read_flight(p)
    assert out["header"]["schema"] == FLIGHT_SCHEMA
    assert out["header"]["flush_every"] == 4
    assert [r["step"] for r in out["records"]] == list(range(8))
    assert out["end"] is None
    # the sidecar checkpoint is consistent with the last flush: it
    # names the newest DURABLE record, not the in-memory tail
    assert out["checkpoint"]["flushed"] == 8
    assert out["checkpoint"]["last"]["step"] == 7


def test_dump_flushes_remainder_and_is_idempotent(tmp_path):
    p = str(tmp_path / "f.jsonl")
    fr = FlightRecorder(p, flush_every=8, fingerprint="fp-test")
    for i in range(5):
        fr.record(step=i)
    assert fr.flushed == 0  # below cadence: nothing durable yet
    assert fr.dump("done", steps=5) == p
    fr.dump("crash")  # second verdict must not rewrite history
    fr.record(step=99)  # post-dump records are dropped, not appended
    out = read_flight(p)
    assert [r["step"] for r in out["records"]] == list(range(5))
    assert out["end"]["status"] == "done"
    assert out["end"]["records"] == 5
    assert out["end"]["fingerprint"] == "fp-test"


def test_reader_tolerates_torn_tail(tmp_path):
    """A SIGKILL mid-append tears at most the final line; the reader
    must count it and keep every complete record."""
    p = str(tmp_path / "f.jsonl")
    fr = FlightRecorder(p, flush_every=2)
    for i in range(4):
        fr.record(step=i)
    with open(p, "a") as f:
        f.write('{"kind": "record", "step": 4, "trunc')  # the tear
    out = read_flight(p)
    assert [r["step"] for r in out["records"]] == [0, 1, 2, 3]
    assert out["torn"] == 1
    assert out["end"] is None


def test_record_never_raises_on_dead_path(tmp_path, capsys):
    """The black box must not be able to kill the loop it watches: an
    unwritable path degrades to in-memory recording + one warning.
    (A regular file poses as the parent dir — NotADirectoryError hits
    even when the suite runs as root, where chmod would not.)"""
    (tmp_path / "nope").write_text("a file, not a directory")
    p = str(tmp_path / "nope" / "f.jsonl")
    fr = FlightRecorder(p, flush_every=1)
    for i in range(3):
        fr.record(step=i)  # must not raise
    assert fr.recorded == 3
    assert fr.flushed == 0
    err = capsys.readouterr().err
    assert err.count("obs.flight") == 1  # warned once, not per record


def test_simulated_hard_death_loses_at_most_one_interval(tmp_path):
    """The fast crash test: a subprocess records steps then
    ``os._exit(9)``s — no finally blocks, no dump(), the exact shape
    of a SIGKILL — and the dump it leaves must be readable, footer-less
    and at most one flush interval behind the death step."""
    p = str(tmp_path / "crash.jsonl")
    n, flush_every = 21, 4
    script = textwrap.dedent(f"""
        import importlib.util, os
        spec = importlib.util.spec_from_file_location(
            "flight", {os.path.join(REPO, 'fluxdistributed_tpu', 'obs', 'flight.py')!r})
        flight = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(flight)
        fr = flight.FlightRecorder({p!r}, flush_every={flush_every})
        for i in range({n}):
            fr.record(step=i, loss=1.0 / (i + 1))
        os._exit(9)  # hard death: no finally, no dump
    """)
    proc = subprocess.run([sys.executable, "-c", script], timeout=60)
    assert proc.returncode == 9
    out = read_flight(p)
    assert out["end"] is None  # the hard-death signature
    assert out["records"], "a crash left no flushed records"
    last = out["records"][-1]["step"]
    assert last >= n - 1 - flush_every, (
        f"lost more than one flush interval: last flushed step {last}, "
        f"death after step {n - 1}, flush_every {flush_every}")
    # the atomic sidecar survived too, consistent with the dump
    assert out["checkpoint"]["flushed"] == len(out["records"])


def test_trainer_wires_flight_records_and_footer(tmp_path):
    """``train(observation=Observation(flight_path=...))`` leaves a
    dump with one record per loader item (step, loss, phase seconds)
    and a ``done`` footer — and registers the ``fdtpu_run_info``
    stitch gauge."""
    from fluxdistributed_tpu import mesh as mesh_lib, optim
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.models import SimpleCNN
    from fluxdistributed_tpu.obs import Observation, Registry
    from fluxdistributed_tpu.train import NullLogger, prepare_training, train

    mesh = mesh_lib.data_mesh(8)
    ds = SyntheticDataset(nsamples=64, nclasses=4, shape=(8, 8, 3))
    task = prepare_training(
        SimpleCNN(num_classes=4), ds, optim.momentum(0.05, 0.9),
        mesh=mesh, batch_size=16, cycles=4)
    p = str(tmp_path / "train-flight.jsonl")
    reg = Registry()
    obs = Observation(registry=reg, flight_path=p)
    train(task, print_every=0, eval_every=0, logger=NullLogger(),
          observation=obs)
    out = read_flight(p)
    assert out["end"]["status"] == "done"
    steps = [r["step"] for r in out["records"]]
    assert steps == sorted(steps) and len(steps) == 4
    rec = out["records"][-1]
    assert isinstance(rec["loss"], float)
    assert "dispatch" in rec["phases"]
    assert rec["opt_step"] == 4
    # the stitch gauge landed on the run's registry, info-style
    assert "fdtpu_run_info{" in reg.prometheus_text()


class _FakeEngine:
    """Pure-python LMEngine stand-in (mirrors tests/test_obs.py): the
    scheduler's flight wiring runs without compiling anything."""

    max_slots = 2

    def validate_request(self, prompt_len, max_new_tokens):
        pass

    def prefill(self, slot, prompt, temperature, key):
        return 7, 8  # (first token, padded bucket size)

    def step_decode(self):
        return [1] * self.max_slots

    def reset_slot(self, slot):
        pass

    def compile_stats(self):
        return {"decode_compiles": 1, "prefill_compiles": 2,
                "insert_compiles": 1}


def test_scheduler_per_tick_records_and_close_footer(tmp_path):
    """The serve scheduler records one line per tick and footers the
    dump on close() — a killed replica's dump names its last tick."""
    from fluxdistributed_tpu.serve import Request, Scheduler

    p = str(tmp_path / "serve-flight.jsonl")
    fr = FlightRecorder(p, flush_every=2)
    sched = Scheduler(_FakeEngine(), max_queue=4, flight=fr)
    sched.submit(Request(prompt=[1, 2, 3], max_new_tokens=3))
    sched.run_until_idle()
    sched.close()
    out = read_flight(p)
    assert out["end"]["status"] == "closed"
    assert out["records"], "no per-tick records"
    ticks = [r["tick"] for r in out["records"]]
    assert ticks == sorted(ticks)
    assert sum(r["emitted"] for r in out["records"]) >= 3
    assert "fdtpu_run_info{" in sched.registry.prometheus_text()


@pytest.mark.slow
def test_real_sigkill_leaves_fresh_dump(tmp_path):
    """The acceptance-criterion shape, for real: SIGKILL a live
    ``bin/driver.py --flight`` run mid-step (no fault plan — an actual
    signal 9 from outside) and the dump must be readable, footer-less
    and within one flush interval of the last step the driver
    reported."""
    p = str(tmp_path / "kill-flight.jsonl")
    cmd = [
        sys.executable, os.path.join(REPO, "bin", "driver.py"),
        "--model", "SimpleCNN", "--dataset", "synthetic",
        "--num-classes", "4", "--image-size", "8",
        "--batch-size", "8", "--cycles", "400",
        "--print-every", "1", "--eval-every", "0",
        "--platform", "cpu", "--local-devices", "2",
        "--flight", p,
    ]
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
           "PYTHONUNBUFFERED": "1"}  # the pipe must see cycle lines live
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                            stderr=subprocess.DEVNULL, env=env)
    seen = -1
    try:
        for line in proc.stdout:
            if line.startswith("cycle "):
                seen = int(line.split()[1])
                if seen >= 12:
                    break
        assert seen >= 12, "driver never reached cycle 12"
        proc.kill()  # SIGKILL: no finally, no dump
    finally:
        proc.stdout.close()
        rc = proc.wait(timeout=60)
    assert rc == -9
    out = read_flight(p)
    assert out["end"] is None, "a SIGKILL must not leave a footer"
    assert out["records"], "no flushed records survived the kill"
    flush_every = out["header"]["flush_every"]
    last = out["records"][-1]["step"]
    # the driver logs "cycle N" before the step runs, so death is at
    # some step >= seen; the last FLUSHED record must be within one
    # flush interval of the last step provably started
    assert last >= seen - flush_every, (
        f"dump is stale: last flushed step {last}, driver reached "
        f"cycle {seen}, flush_every {flush_every}")
